"""Node: the masterless peer orchestrating the token ring.

Parity: /root/reference/xotorch/orchestration/node.py:22-620 — same public
surface (start/stop, process_prompt/process_tensor, enqueue_example/
process_example, coordinate_save, collect_topology, on_token,
on_opaque_status) and the same deterministic-ring design:

- every peer derives the identical partition table from the gossiped topology
  (RingMemoryWeightedPartitioningStrategy), so routing needs no coordination;
- the token ring: the last-layer peer samples, broadcasts the token list to
  all peers, and feeds the token back to partition 0; everyone else forwards
  hidden state to the next partition (bf16 on the wire here — the reference
  upcast to fp32 every hop);
- peers reconcile membership every `topology_interval` seconds and re-gossip
  the topology with a visited-set BFS capped at max_depth.

Training rides the same ring: forward activations down, gradients chained
back (process_example), with the engine-leaf train/evaluate implemented for
real in the JAX engine (the reference's engines never implemented them).
"""
from __future__ import annotations

import asyncio
import json
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from xotorch_tpu.inference.engine import (
  CacheExhausted, InferenceEngine, RequestStateLost, inference_engine_classes,
)
from xotorch_tpu.inference.shard import Shard
from xotorch_tpu.models.registry import get_supported_models
from xotorch_tpu.networking.discovery import Discovery
from xotorch_tpu.networking.peer_handle import PeerHandle
from xotorch_tpu.networking.server import Server
from xotorch_tpu.topology.device_capabilities import UNKNOWN_DEVICE_CAPABILITIES, device_capabilities
from xotorch_tpu.topology.partitioning import PartitioningStrategy, map_partitions_to_shards
from xotorch_tpu.orchestration.tracing import TRACEPARENT_KEY, TraceContext, Tracer
from xotorch_tpu.orchestration.admission import AdmissionGate
from xotorch_tpu.orchestration.alerts import AlertEngine
from xotorch_tpu.orchestration.anatomy import (
  AnatomyStore, ClockSkew, extract_breakdown, ring_offsets,
)
from xotorch_tpu.orchestration.metrics import NodeMetrics, aggregate_histograms
from xotorch_tpu.orchestration.flight import FlightRecorder
from xotorch_tpu.topology.topology import Topology
from xotorch_tpu.utils import knobs
from xotorch_tpu.utils.helpers import DEBUG, AsyncCallbackSystem, spawn_detached

# inference_state side-channel key carrying the per-request completion cap to
# the last-layer peer (companion to tracing.TRACEPARENT_KEY).
MAX_TOKENS_KEY = "xot_max_tokens"
# Same side-channel for the per-request sampling temperature (OpenAI
# `temperature`): whichever peer samples must use the REQUEST's temperature,
# not its own node default.
TEMP_KEY = "xot_temperature"
# And for OpenAI `top_p` (nucleus sampling). Values snap to a 0.05 grid at
# the API so the (top_k, top_p)-specialised executables stay bounded.
TOP_P_KEY = "xot_top_p"
# And for the OpenAI sampling extras the reference parsed-and-dropped
# (chatgpt_api.py): one JSON-safe dict {seed, logit_bias,
# presence_penalty, frequency_penalty} applied on device by the sampler.
SAMPLING_KEY = "xot_sampling"
# Prompt token ids for prompt-lookup speculation on multi-partition rings:
# mid-ring hops carry hidden states, so the SAMPLER peer (which drafts)
# never sees the prompt tokens unless the first-layer owner sends them once
# on the first hop. Only attached when XOT_SPECULATE > 0.
PROMPT_TOKENS_KEY = "xot_prompt_tokens"
# Request-scoped partition map ("routing epoch"): [[node_id, start, end],
# ...] in ring order, pinned ONCE by the node that originates a request and
# carried on the prompt hop and every tensor hop. Every peer routes THIS
# request by the map, not by its own live topology view — a peer that joined
# moments ago (whose gossip/partition view still lags) would otherwise
# recompute a DIFFERENT shard for the same request and serve the wrong layer
# range: the observed failure was a just-joined peer prefilling the full
# model into one engine context while the ring decoded through another,
# silently diverging the stream. Membership changes mid-request still abort
# via hop errors (the map names a peer that no longer answers).
RING_MAP_KEY = "xot_ring_map"
# Remaining end-to-end deadline budget (seconds at send time), riding the
# inference_state side-channel like the traceparent: every peer that touches
# the request derives its own absolute deadline from it, so the watchdog can
# abort a blown request ANYWHERE on the ring (monotonic clocks don't compare
# across hosts — the absolute value never crosses the wire).
DEADLINE_KEY = "xot_deadline_s"


_DRAFT_SCAN_WINDOW = knobs.get_int("XOT_SPECULATE_WINDOW")

# A busy local engine defers a stall-watchdog abort (an in-flight cold-jit
# compile is active work, not a distributed stall) for at most this many
# stall-timeout multiples: one compile fits comfortably, while an engine kept
# permanently busy by OTHER requests cannot shield a dead-peer hang forever.
_STALL_DEFER_CAP = 4


def _lookup_draft(context: List[int], k: int) -> List[int]:
  """Prompt-lookup drafting (model-free speculative decoding): propose the
  continuation of the most recent EARLIER occurrence of the current tail
  n-gram in prompt+output. Summarisation/extraction/code workloads repeat
  long prompt spans verbatim, so drafts verify at high acceptance; on text
  with no repeats this returns [] and decode proceeds normally."""
  if k < 2 or len(context) < 4:
    return []
  # Bound the backward scan: long-context prompts would otherwise pay an
  # O(prompt) Python scan per decode round on the event loop.
  context = context[-_DRAFT_SCAN_WINDOW:]
  for n in (3, 2):
    if len(context) <= n:
      continue
    tail = context[-n:]
    best: List[int] = []
    # Newest occurrence preferred, but keep scanning older ones when the
    # continuation is short — self-repetition's newest match sits right at
    # the tail with almost nothing after it, while older ones run long.
    for i in range(len(context) - n - 1, -1, -1):
      if context[i:i + n] == tail:
        cont = context[i + n:i + n + k]
        if len(cont) == k:
          return cont
        if len(cont) > len(best):
          best = cont
    if len(best) >= 2:
      return best
  return []


class Node:
  def __init__(
    self,
    _id: str,
    server: Server,
    inference_engine: InferenceEngine,
    discovery: Discovery,
    shard_downloader,
    partitioning_strategy: PartitioningStrategy,
    max_generate_tokens: int = 1024,
    default_sample_temp: float = 0.6,
    default_sample_top_k: int = 35,
    topology_viz=None,
    decode_chunk_size: Optional[int] = None,
  ):
    self.id = _id
    self.server = server
    self.inference_engine = inference_engine
    self.discovery = discovery
    self.shard_downloader = shard_downloader
    self.partitioning_strategy = partitioning_strategy
    self.max_generate_tokens = max_generate_tokens
    self.default_sample_temp = default_sample_temp
    self.default_sample_top_k = default_sample_top_k
    self.topology_viz = topology_viz
    # Tokens per fused decode dispatch when one partition owns the whole
    # model; 1 disables (pure per-token ring). Bounds both streaming latency
    # and the EOS overshoot (tokens computed past EOS are discarded).
    self.decode_chunk_size = (
      decode_chunk_size if decode_chunk_size is not None
      else knobs.get_int("XOT_DECODE_CHUNK")
    )
    # Adaptive growth ceiling: each fused dispatch doubles the chunk up to
    # this cap, so long generations amortise the per-dispatch host sync
    # (~O(100ms) on tunneled TPUs) while the FIRST chunk stays small for
    # streaming latency and short replies never overshoot far past EOS.
    # Power-of-two ladder => bounded executable count per (B, size) pair.
    self.max_decode_chunk_size = max(
      self.decode_chunk_size, knobs.get_int("XOT_DECODE_CHUNK_MAX")
    )

    self.peers: List[PeerHandle] = []
    self.topology = Topology()
    self.device_capabilities = UNKNOWN_DEVICE_CAPABILITIES
    self.buffered_token_output: Dict[str, Tuple[List[int], bool]] = {}
    self.checkpoints: Dict[str, Dict[str, int]] = {}
    self.topology_inference_engines_pool: List[List[str]] = []
    self.node_download_progress: Dict[str, Any] = {}

    self.on_token: AsyncCallbackSystem = AsyncCallbackSystem()
    self.on_opaque_status: AsyncCallbackSystem = AsyncCallbackSystem()
    self.on_opaque_status.register("node_status").on_next(self.on_node_status)

    self._topology_task: Optional[asyncio.Task] = None
    self.outstanding_requests: Dict[str, str] = {}

    # Observability: real spans + real prometheus metrics for the intents the
    # reference declared but never wired (SURVEY §0, §5), plus the always-on
    # flight recorder whose frozen snapshots turn watchdog aborts into
    # replayable timelines (/v1/debug/flight).
    self.tracer = Tracer(node_id=self.id)
    self.metrics = NodeMetrics(node_id=self.id)
    self.flight = FlightRecorder(node_id=self.id)
    self._request_trace_ctx: Dict[str, Any] = {}
    self._last_token_time: Dict[str, float] = {}
    # First-touch monotonic timestamp per request — feeds the TTFT and
    # whole-request SLO histograms (each node observes its own view).
    self._request_started: Dict[str, float] = {}
    # Latest metric summaries received from peers over the status bus
    # (type "node_metrics"); served by /v1/cluster/metrics so one scrape
    # sees the whole ring. Bounded by cluster size in practice; the LRU
    # guard protects against id churn. Each ingest is stamped (monotonic)
    # so a dead node's last-good summary reads STALE past 3x the topology
    # cadence instead of polluting the cluster aggregate forever, and
    # eviction prunes the row outright.
    self.peer_metrics: "OrderedDict[str, dict]" = OrderedDict()
    self._peer_metrics_at: Dict[str, float] = {}
    # Topology-reconcile cadence (start() overwrites with the real value):
    # the staleness horizon for peer_metrics rows is 3x this.
    self.topology_interval = 2.0
    # Engine-depth observability: hand the engine this node's recorder,
    # metrics registry, tracer, and a trace-context resolver so batcher
    # queue waits, prefill slices, pool pressure, host-tier traffic, and
    # first-compile events surface as spans/histograms/flight events.
    # Duck-typed (base-class attrs default None): every engine accepts the
    # hooks, engines that never call them pay nothing.
    for hook, value in (("metrics", self.metrics), ("flight", self.flight),
                        ("tracer", self.tracer),
                        ("trace_ctx", self._request_trace_ctx.get)):
      try:
        setattr(inference_engine, hook, value)
      except Exception as e:
        if DEBUG >= 2:
          print(f"engine observability hook {hook} not attached: {e!r}")
    # Per-request completion caps (OpenAI max_tokens); rides the
    # inference_state side-channel to whichever peer owns the last layer.
    self._request_max_tokens: Dict[str, int] = {}
    # Per-request sampling temperature (OpenAI temperature); same channel.
    self._request_temp: Dict[str, float] = {}
    # Per-request nucleus sampling (OpenAI top_p); same channel.
    self._request_top_p: Dict[str, float] = {}
    # Per-request sampling extras (OpenAI seed / logit_bias / penalties);
    # same channel (SAMPLING_KEY).
    self._request_sampling: Dict[str, dict] = {}
    # Does engine.infer_sample_tensor accept the `sampling` kwarg? Resolved
    # by signature inspection on first extras request (None = not yet).
    self._engine_accepts_sampling: Optional[bool] = None
    # Why a request aborted (bounded LRU; API pops entries when reporting).
    self.request_errors: "OrderedDict[str, str]" = OrderedDict()
    # Request ids whose finish broadcast was applied here (bounded): shields
    # against out-of-order straggler deltas resurrecting finished requests.
    self._finished_results: "OrderedDict[str, None]" = OrderedDict()
    # Per-request EOS id cache: constant over a request's lifetime; avoids a
    # ring-partition recompute per sampled token on the per-token path.
    self._request_eos: Dict[str, Tuple[int, ...]] = {}
    # Prompt token ids per request (sampler peer only): the draft source for
    # prompt-lookup speculative decoding (XOT_SPECULATE).
    self._request_prompt_tokens: Dict[str, List[int]] = {}
    # Per-request partition map (RING_MAP_KEY): ring-ordered
    # [node_id, start_layer, end_layer] rows, pinned at request origin.
    self._request_ring_map: "OrderedDict[str, list]" = OrderedDict()
    # Serializes peer-set reconciliation (periodic loop + hop-time heals).
    self._update_peers_lock = asyncio.Lock()
    # Client-cancelled requests (cancel_request): the decode loops stop at
    # the next token/chunk boundary instead of running to EOS/cap. Bounded
    # LRU rather than per-request cleanup: the flag must outlive
    # finish_request_state so a still-running loop (possibly on a REMOTE
    # sampler peer, marked via the finished broadcast) reliably observes it.
    self._cancelled: "OrderedDict[str, None]" = OrderedDict()
    # Draft-MODEL speculation (XOT_DRAFT_MODEL): a small resident model
    # proposes every round (engine.draft_tokens) where prompt-lookup only
    # fires on n-gram repeats. Setting a draft model implies speculation on
    # (default 8 draft tokens; XOT_SPECULATE still overrides the depth).
    self.draft_model = knobs.get_str("XOT_DRAFT_MODEL", "")
    self.speculate_tokens = knobs.get_int("XOT_SPECULATE", 8 if self.draft_model else 0)
    # Strong refs to detached tasks (hops, fused loops, broadcasts): the
    # event loop holds tasks only weakly — a GC'd generation-driving task
    # would silently stall its request with no error.
    self._detached_tasks: set = set()

    # ---- request survivability (deadlines, watchdog, eviction) ----
    # End-to-end request deadline (0 disables); remaining budget rides the
    # hops (DEADLINE_KEY / send_prompt's deadline field).
    self.request_deadline_s = knobs.get_float("XOT_REQUEST_DEADLINE_S")
    # Stall watchdog: abort any request whose last observed progress (hop
    # received / token sampled / broadcast delta applied) is older than
    # this (0 disables) — a peer that dies AFTER acking a tensor otherwise
    # stalls the request forever with no error anywhere.
    self.stall_timeout_s = knobs.get_float("XOT_STALL_TIMEOUT_S")
    # Periodic peer health monitor (0 disables): a peer failing
    # XOT_HEALTH_FAILS consecutive checks is evicted and the topology
    # repartitioned; eviction holds for XOT_EVICT_COOLDOWN_S so discovery
    # can't immediately re-admit a corpse.
    self.health_interval_s = knobs.get_float("XOT_HEALTH_INTERVAL_S")
    self.health_fail_threshold = max(1, knobs.get_int("XOT_HEALTH_FAILS"))
    self.evict_cooldown_s = knobs.get_float("XOT_EVICT_COOLDOWN_S")
    self._request_deadline: Dict[str, float] = {}
    self._last_progress: Dict[str, float] = {}
    # Requests whose stall abort was deferred because the local engine was
    # mid-dispatch (compile included): tracked so the flight recorder logs
    # ONE `watchdog.deferred` per stall episode, not one per sweep tick.
    self._stall_deferred: set = set()
    # Receiver-side hop dedup: per-request bounded seen-sets of hop seq ids
    # (note_hop_delivery) — what makes retried deliveries idempotent.
    self._hop_seen: "OrderedDict[str, OrderedDict]" = OrderedDict()
    self._health_fails: Dict[str, int] = {}
    self._evicted_until: Dict[str, float] = {}
    self._watchdog_task: Optional[asyncio.Task] = None
    self._health_task: Optional[asyncio.Task] = None
    # Metrics history (XOT_HISTORY, default on): a bounded downsampling
    # time-series of this node's own windowed gauge deltas, optionally
    # spooled to XOT_HISTORY_DIR so restarts keep the record. Served at
    # /v1/history; its trailing compact rides metrics_summary() so ring
    # peers (and the router) can run peer-median drift comparisons.
    # Constructed BEFORE the alert engine: the engine's DriftSentinel
    # reads it on every evaluate tick.
    from xotorch_tpu.orchestration.history import MetricsHistory
    self.history = MetricsHistory(self)
    self._history_task: Optional[asyncio.Task] = None
    # SLO burn-rate alerts + gray-failure localization (XOT_ALERT, default
    # on): evaluated on a background cadence over windowed deltas of this
    # node's own metric summaries; served at /v1/alerts and rolled over the
    # status bus via metrics_summary().
    self.alerts = AlertEngine(self)
    self._alert_task: Optional[asyncio.Task] = None
    # Bounded admission gate (XOT_MAX_INFLIGHT, default 0 = off): the API
    # acquires a slot before process_prompt, so overload is shed as 429s at
    # the door instead of watchdog "stalled" aborts inside the ring.
    # Exposed at /v1/queue; the compact rides metrics_summary() while
    # enabled so the router (and peers) place by live load.
    self.admission = AdmissionGate(self)
    # Anticipatory-prefetch dedupe (bounded LRU of (shard, prompt-hash) ->
    # monotonic ts): the router's /v1/prefetch pre-announce and the
    # admission gate's on_queued hook fire for the SAME queued request, and
    # the duplicate would re-run tokenizer encode + host-store match on a
    # node that is by definition saturated.
    self._prefetch_recent: "OrderedDict[tuple, float]" = OrderedDict()
    # Critical-path latency anatomy (XOT_ANATOMY, default on): per-peer
    # clock-skew estimation fed by hop clock stamps (receive side:
    # note via `self.clock`; send side: peer handles adopt `self.clock` at
    # peer-set assignment, like `flight`), plus a bounded reservoir of
    # skew-corrected per-request stage breakdowns assembled at the ORIGIN
    # once the ring's trace shards arrive. Served at /v1/anatomy.
    self.clock = ClockSkew(self.id)
    # Spans stamp through the same (possibly skew-injected) wall clock as
    # the hop stamps, so XOT_ANATOMY_SKEW_NS simulates a skewed host end
    # to end — spans drift exactly as far as the stamps that correct them.
    self.tracer.now_ns = self.clock.wall_ns
    self.anatomy = AnatomyStore()
    self._anatomy_delay_s = max(0.0, knobs.get_float("XOT_ANATOMY_DELAY_S"))
    # Requests THIS node originated (bounded LRU): only the origin holds
    # the rolled-up trace, so only it assembles the breakdown.
    self._anatomy_origin: "OrderedDict[str, None]" = OrderedDict()

  def _spawn(self, coro) -> "asyncio.Task":
    return spawn_detached(coro, self._detached_tasks)

  # ------------------------------------------------------------- lifecycle

  async def start(self, wait_for_peers: int = 0, topology_interval: float = 2.0) -> None:
    self.device_capabilities = await device_capabilities()
    self.topology_interval = topology_interval
    await self.server.start()
    await self.discovery.start()
    await self.update_peers(wait_for_peers)
    await self.collect_topology(set())
    self._topology_task = self._spawn(self.periodic_topology_collection(topology_interval))
    self.start_watchdog()
    self.start_health_monitor()
    self.start_alerts()
    self.start_history()
    if DEBUG >= 1:
      print(f"Node {self.id} started; topology: {self.topology}")

  async def stop(self) -> None:
    for attr in ("_topology_task", "_watchdog_task", "_health_task", "_alert_task",
                 "_history_task"):
      task = getattr(self, attr)
      if task is not None:
        task.cancel()
        try:
          await task
        except asyncio.CancelledError:
          pass
        setattr(self, attr, None)
    await self.discovery.stop()
    await self.server.stop()
    # Detached graceful channel drains (peer replacement mid-request) must
    # not outlive the node: settle them with a short grace, cancel the rest.
    try:
      from xotorch_tpu.networking.grpc.peer_handle import drain_graceful_closes
      await drain_graceful_closes()
    except ImportError:
      pass  # grpc-less deployments (in-process ring) have none

  # ------------------------------------------------------- survivability

  def start_watchdog(self, request_id: Optional[str] = None) -> None:
    """Arm the deadline/stall watchdog (no-op when nothing needs it).
    Also called lazily from _note_progress / deadline adoption so Nodes
    driven without start() — the test harness pattern — still get
    coverage, and a peer whose OWN knobs are off still enforces a deadline
    that arrived via hop metadata (the origin may be the node that died).
    `request_id` is the request whose progress/deadline triggered the lazy
    arming — recorded so a flight snapshot shows the arming→firing pair."""
    if self._watchdog_task is None and (
        self.stall_timeout_s > 0 or self.request_deadline_s > 0 or self._request_deadline):
      self._watchdog_task = self._spawn(self._watchdog_loop())
      self.flight.record("watchdog.armed", request_id,
                         stall_s=self.stall_timeout_s, deadline_s=self.request_deadline_s)

  def start_health_monitor(self) -> None:
    if self._health_task is None and self.health_interval_s > 0:
      self._health_task = self._spawn(self._health_monitor_loop())

  def start_alerts(self) -> None:
    if self._alert_task is None and self.alerts.enabled:
      self._alert_task = self._spawn(self._alert_loop())

  def start_history(self) -> None:
    if self._history_task is None and self.history.enabled:
      self._history_task = self._spawn(self._history_loop())

  async def _history_loop(self) -> None:
    """Metrics-history sampling cadence: one windowed gauge sample per
    tick. Host-side reads only (metric cells, engine counters, EWMAs) —
    this loop can never add a device sync."""
    while True:
      await asyncio.sleep(self.history.sample_s)
      try:
        self.history.observe()
      except Exception as e:
        if DEBUG >= 1:
          print(f"history sampling error: {e!r}")

  async def _alert_loop(self) -> None:
    """SLO rule evaluation cadence: snapshot the node's own metric summary,
    difference it at the burn windows, step each rule's state machine.
    Host-side reads only — this loop can never add a device sync."""
    while True:
      await asyncio.sleep(self.alerts.eval_interval_s)
      try:
        self.alerts.evaluate()
      except Exception as e:
        if DEBUG >= 1:
          print(f"alert evaluation error: {e!r}")

  def _note_progress(self, request_id: str) -> None:
    self._last_progress[request_id] = time.monotonic()
    self._stall_deferred.discard(request_id)
    self.start_watchdog(request_id)

  def note_hop_delivery(self, request_id: Optional[str], hop_seq: Optional[str]) -> bool:
    """Receiver-side dedup for retried hops: True admits the delivery, False
    means this (request, seq) was already delivered — the sender's ack got
    lost and its retry redelivered; processing it again would double-decode
    a position. Bounded per-request seen-sets (retries land close in time,
    so a small window suffices); rows age out of the bounded LRU rather
    than dying at finish, so a retry landing after the request completed is
    still dropped instead of resurrecting state for a dead request."""
    if hop_seq is None:
      return True
    key = request_id or ""
    seen = self._hop_seen.get(key)
    if seen is None:
      seen = self._hop_seen[key] = OrderedDict()
      while len(self._hop_seen) > 256:
        self._hop_seen.popitem(last=False)
    self._hop_seen.move_to_end(key)
    if hop_seq in seen:
      self.metrics.dedup_drops_total.inc()
      self.flight.record("hop.dedup_drop", request_id, seq=hop_seq)
      if DEBUG >= 2:
        print(f"[{request_id}] duplicate hop delivery {hop_seq} dropped")
      return False
    seen[hop_seq] = None
    while len(seen) > 128:
      seen.popitem(last=False)
    return True

  async def _watchdog_loop(self) -> None:
    """Abort requests that blew their end-to-end deadline or stopped making
    progress. Today's alternative is a silent forever-hang: a peer that
    dies after acking a tensor raises no error anywhere. Aborting rides the
    existing _abort_request path, so the finish broadcast cleans up
    bookkeeping and KV on every surviving peer too."""
    bounds = [t for t in (self.stall_timeout_s, self.request_deadline_s) if t > 0]
    tick = min(1.0, max(0.02, min(bounds) / 4)) if bounds else 1.0
    while True:
      await asyncio.sleep(tick)
      now = time.monotonic()
      try:
        for rid, dl in list(self._request_deadline.items()):
          if now <= dl:
            continue
          if rid in self.outstanding_requests or rid in self.buffered_token_output:
            self.metrics.watchdog_aborts_total.inc()
            self.flight.record("deadline.expired", rid, overdue_s=round(now - dl, 3))
            self.flight.record("watchdog.fired", rid, kind="deadline")
            await self._abort_request(rid, f"deadline_exceeded: request blew its deadline on {self.id}")
          else:
            self._request_deadline.pop(rid, None)  # finished elsewhere; GC the row
        if self.stall_timeout_s > 0:
          # Sweep every request with a progress row, not just locally
          # outstanding ones: the ORIGIN of a forwarded prompt returns
          # right after the forward (it is never "outstanding" here), yet a
          # silently lost prompt chain must still end at its deadline
          # instead of riding the API timeout. Rows die at finish, so a
          # completed request can't false-abort.
          busy_fn = getattr(self.inference_engine, "dispatch_inflight", None)
          for rid in set(self.outstanding_requests) | set(self._last_progress):
            last = self._last_progress.get(rid)
            if last is None:
              self._last_progress[rid] = now
            elif now - last > self.stall_timeout_s:
              if (busy_fn is not None and busy_fn()
                  and now - last <= self.stall_timeout_s * _STALL_DEFER_CAP):
                # The local engine is mid-dispatch (a cold-jit compile of a
                # first request can exceed any sane stall bound): this is
                # active work, not the silent distributed stall the watchdog
                # exists for. Defer — the stall clock keeps running, so the
                # abort fires at the first sweep that finds the engine idle.
                # BOUNDED: on a busy ring the engine is mid-dispatch at
                # almost every sweep serving OTHER requests, which must not
                # shield a dead-peer hang forever — past the cap the abort
                # fires regardless. A hung DEVICE call is the request
                # deadline's job.
                if rid not in self._stall_deferred:
                  self._stall_deferred.add(rid)
                  self.flight.record("watchdog.deferred", rid,
                                     idle_s=round(now - last, 3))
                continue
              self._stall_deferred.discard(rid)
              self.metrics.watchdog_aborts_total.inc()
              self.flight.record("watchdog.fired", rid, kind="stall",
                                 idle_s=round(now - last, 3))
              await self._abort_request(
                rid, f"stalled: no progress for {now - last:.2f}s on {self.id} "
                     f"(stall timeout {self.stall_timeout_s:g}s)")
      except Exception as e:
        if DEBUG >= 1:
          print(f"watchdog error: {e!r}")

  async def _health_monitor_loop(self) -> None:
    """Periodic wiring for the (previously never-called) peer health_check:
    evict peers that fail repeatedly and repartition, so the NEXT request
    pins a ring of live peers instead of routing into a corpse."""
    while True:
      await asyncio.sleep(self.health_interval_s)
      try:
        await self._health_sweep(self.health_fail_threshold)
      except Exception as e:
        if DEBUG >= 1:
          print(f"health monitor error: {e!r}")

  async def _health_sweep(self, evict_after: int) -> None:
    for peer in list(self.peers):
      try:
        ok = await peer.health_check()
      except Exception:
        ok = False
      if ok:
        self._health_fails.pop(peer.id(), None)
        continue
      from xotorch_tpu.networking import faults
      faults.bump("health_check_failures")
      fails = self._health_fails.get(peer.id(), 0) + 1
      self._health_fails[peer.id()] = fails
      self.flight.record("health.check_failed", None, peer=peer.id(), fails=fails)
      if fails >= evict_after:
        await self._evict_peer(peer)

  async def _evict_peer(self, peer) -> None:
    if DEBUG >= 1:
      print(f"Evicting unhealthy peer {peer.id()}@{peer.addr()}")
    self.peers = [p for p in self.peers if p.id() != peer.id()]
    self._evicted_until[peer.id()] = time.monotonic() + self.evict_cooldown_s
    self._health_fails.pop(peer.id(), None)
    # A dead peer's last-good metric summary must not keep feeding the
    # cluster aggregate (it would freeze the ring's percentiles at the
    # moment of death).
    self.peer_metrics.pop(peer.id(), None)
    self._peer_metrics_at.pop(peer.id(), None)
    self.metrics.peer_evictions_total.inc()
    self.metrics.peers.set(len(self.peers))
    self.flight.record("peer.evicted", None, peer=peer.id(),
                       cooldown_s=self.evict_cooldown_s)
    # An eviction is a terminal anomaly for whatever was riding that peer:
    # freeze a node-scope snapshot now (in-flight requests usually follow
    # with their own watchdog/hop-error freeze via _abort_request).
    self.flight.freeze(None, reason=f"peer_evicted:{peer.id()}")
    try:
      await peer.disconnect()
    except Exception as e:
      if DEBUG >= 1:
        print(f"evicted peer {peer.id()} disconnect failed (already dead?): {e!r}")
    try:
      # Repartition NOW: the dead peer must leave the partition table before
      # any new (or restarted) request pins its ring map.
      await self.collect_topology(set())
    except Exception as e:
      if DEBUG >= 1:
        print(f"post-eviction repartition failed (next periodic sweep retries): {e!r}")

  def _is_evicted(self, peer_id: str) -> bool:
    until = self._evicted_until.get(peer_id)
    if until is None:
      return False
    if time.monotonic() >= until:
      self._evicted_until.pop(peer_id, None)
      return False
    return True

  async def heal_ring(self) -> None:
    """Aggressive one-shot heal for the API's request-restart path: a
    request just died, so a single failed check is enough to evict; then
    re-derive the partition table so the restarted request pins a live
    ring. Peers that pass stay — an engine-side failure must not cost a
    healthy peer its seat."""
    await self._health_sweep(evict_after=1)
    try:
      await self.collect_topology(set())
    except Exception as e:
      if DEBUG >= 1:
        print(f"heal_ring repartition failed (restart will pin the stale map): {e!r}")

  # ----------------------------------------------------------- status bus

  def on_node_status(self, request_id, opaque_status) -> None:
    """Ingest cluster-wide opaque status (parity node.py:73-98): track which
    node is actively serving, download progress, engine pools — feeds viz."""
    try:
      status = json.loads(opaque_status)
      status_type = status.get("type", "")
      if status_type == "supported_inference_engines":
        self.topology_inference_engines_pool.append(status.get("engines", []))
      elif status_type == "download_progress":
        self.node_download_progress[status.get("node_id")] = status.get("progress")
      elif status_type == "trace_spans":
        # Cluster trace rollup (receiver side): adopt a peer's finished
        # spans so a single /v1/traces call on ANY node returns the whole
        # ring's trace for a request. Own broadcasts echo locally — skip.
        if status.get("node_id") != self.id:
          self.tracer.ingest(status.get("spans") or [])
      elif status_type == "node_metrics":
        nid = status.get("node_id")
        if nid and nid != self.id:
          self.ingest_peer_metrics(nid, status.get("metrics") or {})
      elif status_type == "resume_checkpoint":
        # Cluster-wide resume: each peer loads ITS layer range from the
        # shared checkpoint directory, so a multi-partition training ring
        # never restarts as a chimera of resumed + fresh shards.
        if status.get("node_id") != self.id:
          base = Shard.from_dict(status.get("base_shard", {}))
          path = status.get("path", "")
          self._spawn(self._resume_local(base, path))
      elif status_type == "node_status":
        if status.get("status", "").startswith("start_"):
          self.topology.active_node_id = status.get("node_id")
          base = status.get("base_shard") or {}
          if self.topology_viz is not None and base.get("n_layers"):
            # The active model's REAL depth drives the displayed layer
            # ranges (VERDICT r3 weak #5: a hardcoded 32 was wrong for
            # every other model).
            self.topology_viz.update_model(base.get("model_id"), base.get("n_layers"))
          # Adopt the origin's trace context before any tensor hop arrives so
          # even peers that only observe the request join its trace.
          rid = status.get("request_id")
          tp = status.get("traceparent")
          if rid and tp and rid not in self._request_trace_ctx:
            ctx = TraceContext.from_traceparent(tp)
            if ctx is not None:
              self._request_trace_ctx[rid] = ctx
        elif status.get("status", "").startswith("end_"):
          if status.get("node_id") == self.topology.active_node_id:
            self.topology.active_node_id = None
      if self.topology_viz is not None:
        self.topology_viz.update_visualization(self.topology, self.partitioning_strategy.partition(self.topology), self.id)
    except Exception as e:
      if DEBUG >= 2:
        print(f"on_node_status error: {e!r}")

  # ------------------------------------------------------------ inference

  async def process_prompt(self, base_shard: Shard, prompt: str, request_id: Optional[str] = None,
                           traceparent: Optional[str] = None, max_tokens: Optional[int] = None,
                           images: Optional[List[np.ndarray]] = None,
                           temperature: Optional[float] = None,
                           top_p: Optional[float] = None,
                           sampling: Optional[dict] = None,
                           ring_map: Optional[list] = None,
                           deadline: Optional[float] = None) -> None:
    if request_id is None:
      request_id = str(uuid.uuid4())
    if request_id not in self._request_deadline:
      # A forwarded prompt carries the origin's REMAINING budget; an origin
      # request starts a fresh one from the node knob.
      if deadline is not None:
        self._request_deadline[request_id] = time.monotonic() + max(0.0, float(deadline))
      elif self.request_deadline_s > 0:
        self._request_deadline[request_id] = time.monotonic() + self.request_deadline_s
    self._request_started.setdefault(request_id, time.monotonic())
    self.flight.record("request.admitted", request_id, model=base_shard.model_id,
                       origin=traceparent is None)
    self._note_progress(request_id)
    if traceparent is None:
      # Test/soak-only latency tap: injector rules matching rpc
      # "ProcessPrompt" apply at the ORIGIN, after the request's first-touch
      # clock is stamped — the gray-failure shape for a SINGLE-node replica
      # where no peer hop exists to delay. A delay here lands in this node's
      # own TTFT/e2e SLO histograms (so its burn-rate alerts fire exactly
      # like a real slowdown) while /healthcheck stays green — the PR 9
      # delayed-but-health-green scenario the router must act on. With no
      # injector installed this costs one function call per origin request.
      # Gated on a rule that EXPLICITLY names this rpc: wildcard (rpc-less)
      # rules keep their historical peer-handle-boundary semantics and
      # never have their nth/times budget consumed at the origin. (A spec
      # mixing an explicit ProcessPrompt rule with wildcard rules shares
      # one injector, so the wildcard rules' counters do advance on origin
      # taps — name the rpc on both when that matters.)
      from xotorch_tpu.networking import faults
      inj = faults.active()
      if inj is not None and any(r.rpc == "ProcessPrompt" for r in inj.rules):
        try:
          await inj.apply("ProcessPrompt", None)
        except faults.TransientHopError as e:
          await self._abort_request(request_id, f"injected fault on {self.id}: {e}")
          return
    if ring_map:
      # Forwarded prompt: route by the SENDER's pinned map, not our own
      # (possibly lagging) partition view — see RING_MAP_KEY.
      if request_id not in self._request_ring_map:
        self._set_ring_map(request_id, ring_map)
    else:
      self._pin_ring_map(base_shard, request_id)
    shard = self.get_current_shard(base_shard, request_id=request_id)
    if max_tokens is not None:
      # Per-request completion cap (OpenAI max_tokens); the node-wide
      # max_generate_tokens stays the hard ceiling.
      self._request_max_tokens[request_id] = self._clamp_max_tokens(max_tokens)
    if temperature is not None:
      # Per-request sampling temperature (OpenAI temperature); the node
      # default applies only when the request doesn't specify one.
      self._request_temp[request_id] = max(0.0, float(temperature))
    if top_p is not None:
      self._request_top_p[request_id] = min(1.0, max(0.0, float(top_p)))
    if sampling:
      # OpenAI extras (seed / logit_bias / penalties), validated at the API.
      self._request_sampling[request_id] = dict(sampling)
    start_ns = time.perf_counter_ns()
    if traceparent is None:
      # Count only origin requests: a forwarded prompt re-enters process_prompt
      # on the partition-0 owner and would double the cluster-wide sum.
      self.metrics.requests_total.inc()
      if self.anatomy.enabled:
        # Only the origin assembles anatomy: it holds the rolled-up trace.
        self._anatomy_origin[request_id] = None
        self._anatomy_origin.move_to_end(request_id)
        while len(self._anatomy_origin) > 512:
          self._anatomy_origin.popitem(last=False)
    # A forwarded prompt carries the origin node's trace context; joining it
    # keeps one trace per request across the ring (reference tracing.py:36-70).
    parent_ctx = TraceContext.from_traceparent(traceparent)
    with self.tracer.start_span(
      "process_prompt" if parent_ctx is None else "process_prompt.forwarded",
      parent=parent_ctx,
      attributes={"request.id": request_id, "model.id": base_shard.model_id},
    ) as span:
      # The request's root span context rides the status bus + tensor hops so
      # every peer's hop spans join the same trace (reference tracing.py:36-70).
      self._request_trace_ctx[request_id] = span.context()
      self._spawn(self.broadcast_opaque_status(request_id, json.dumps({
        "type": "node_status", "node_id": self.id, "status": "start_process_prompt",
        "base_shard": base_shard.to_dict(), "shard": shard.to_dict(),
        "prompt": prompt, "request_id": request_id,
        "traceparent": span.context().traceparent(),
      })))
      try:
        await self._process_prompt(base_shard, prompt, request_id, images)
      except CacheExhausted as e:
        # Prefill overflow: the prompt itself doesn't fit the KV budget. If
        # any tokens were already produced, end as a normal truncated
        # completion (the decode side's path); a pure-prefill overflow is a
        # client error the API answers with 400 context_length_exceeded —
        # never a 500 (ADVICE r1 (d); ref chatgpt_api.py:357-438 semantics).
        tokens, _ = self.buffered_token_output.get(request_id, ([], False))
        if tokens:
          await self._finish_as_length(request_id)
        else:
          if DEBUG >= 1:
            print(f"[{request_id}] prompt exceeds cache: {e}")
          await self._abort_request(request_id, f"context_length_exceeded: {e}")
      except Exception as e:
        print(f"Error processing prompt [{request_id}]: {e!r}")
        if DEBUG >= 2:
          import traceback
          traceback.print_exc()
        await self._abort_request(request_id, f"prompt processing failed on {self.id}: {e!r}")
    self._spawn(self.broadcast_opaque_status(request_id, json.dumps({
      "type": "node_status", "node_id": self.id, "status": "end_process_prompt",
      "request_id": request_id, "elapsed_time_ns": time.perf_counter_ns() - start_ns,
    })))

  async def _process_prompt(self, base_shard: Shard, prompt: str, request_id: str,
                            images: Optional[List[np.ndarray]] = None) -> None:
    shard = self.get_current_shard(base_shard, request_id=request_id)
    if not shard.is_first_layer:
      # Not our turn: hand the prompt to the partition-0 owner and stop.
      await self.forward_prompt(base_shard, prompt, request_id, 0, images)
      return
    # In a multi-partition ring the EOS/max decision is made by the
    # last-layer peer; forward_prompt carries the cap there (see below).
    self.outstanding_requests[request_id] = "processing prompt"
    self.metrics.active_requests.set(len(self.outstanding_requests))
    sampler = getattr(self.inference_engine, "infer_sample_tensor", None)
    if shard.is_last_layer and sampler is not None and not images:
      # Single-partition text prompt: prefill + on-device sampling in one
      # engine call — the host never sees the prompt's logits.
      tokens = await self.inference_engine.encode(shard, prompt)
      if self.speculate_tokens > 0:
        self._request_prompt_tokens[request_id] = [int(t) for t in np.asarray(tokens).reshape(-1)]
      token, _ = await sampler(
        request_id, shard, np.asarray(tokens).reshape(1, -1),
        temp=self._temp_for(request_id), top_k=self.default_sample_top_k,
        top_p=self._top_p_for(request_id),
        **self._sampling_kwargs(request_id),
      )
      await self.process_sampled_token(base_shard, int(token), request_id, None)
      return
    result, inference_state = await self.inference_engine.infer_prompt(
      request_id, shard, prompt, images=images,
      **self._keep_on_device_kwargs(shard, request_id),
    )
    if (self.speculate_tokens > 0 and not shard.is_last_layer and not images
        and self._inprocess_chain(base_shard, request_id) is not None):
      # Ship the prompt ids to the sampler peer once (first hop's state):
      # prompt-lookup drafting needs tokens, and mid-ring hops are hidden
      # states only. Only for co-located chains — the fused ring (the only
      # consumer of ring speculation) requires them, and a network ring
      # would pay the wire bytes for nothing. The extra tokenize is the
      # price of keeping engine.infer_prompt's one-call contract.
      try:
        toks = await self.inference_engine.encode(shard, prompt)
        inference_state = {**(inference_state or {}),
                           PROMPT_TOKENS_KEY: [int(t) for t in np.asarray(toks).reshape(-1)]}
      except Exception as e:
        # Speculation degrades to output-only drafting; the request itself
        # is unaffected, but log why draft acceptance just dropped.
        if DEBUG >= 1:
          print(f"[{request_id}] prompt tokenize for speculation failed: {e!r}")
    await self.process_inference_result(base_shard, result, request_id, inference_state)

  async def process_tensor(self, base_shard: Shard, tensor: np.ndarray, request_id: Optional[str] = None,
                           inference_state: Optional[dict] = None) -> None:
    if request_id is None:
      request_id = str(uuid.uuid4())
    if inference_state and request_id not in self._request_ring_map:
      m = inference_state.get(RING_MAP_KEY)
      if m:
        self._set_ring_map(request_id, m)
    shard = self.get_current_shard(base_shard, request_id=request_id)
    start_ns = time.perf_counter_ns()
    self.outstanding_requests[request_id] = "processing tensor"
    self.metrics.active_requests.set(len(self.outstanding_requests))
    self.metrics.tensor_hops_total.inc()
    self._request_started.setdefault(request_id, time.monotonic())
    self.flight.record("hop.recv", request_id,
                       layers=f"{shard.start_layer}-{shard.end_layer}")
    self._note_progress(request_id)
    if inference_state and request_id not in self._request_deadline:
      d = inference_state.get(DEADLINE_KEY)
      if d is not None:
        self._request_deadline[request_id] = time.monotonic() + max(0.0, float(d))
        self.start_watchdog()  # a hop-carried deadline must be enforced HERE too
    # Join the request's trace: the traceparent rides the inference_state
    # side-channel across peers (W3C propagation, reference tracing.py:36-70).
    ctx = self._request_trace_ctx.get(request_id)
    if ctx is None and inference_state:
      ctx = TraceContext.from_traceparent(inference_state.get(TRACEPARENT_KEY))
      if ctx is not None:
        self._request_trace_ctx[request_id] = ctx
    if inference_state and request_id not in self._request_max_tokens:
      cap = inference_state.get(MAX_TOKENS_KEY)
      if cap is not None:
        self._request_max_tokens[request_id] = self._clamp_max_tokens(cap)
    if inference_state and request_id not in self._request_temp:
      t = inference_state.get(TEMP_KEY)
      if t is not None:
        self._request_temp[request_id] = max(0.0, float(t))
    if inference_state and request_id not in self._request_top_p:
      p = inference_state.get(TOP_P_KEY)
      if p is not None:
        self._request_top_p[request_id] = min(1.0, max(0.0, float(p)))
    if inference_state and request_id not in self._request_sampling:
      s = inference_state.get(SAMPLING_KEY)
      if s:
        self._request_sampling[request_id] = dict(s)
    if inference_state and request_id not in self._request_prompt_tokens:
      # Only the SAMPLER (last-layer peer) consumes the prompt ids — a
      # mid-ring node on a 3+-partition ring must forward them untouched or
      # the drafting peer never sees them.
      if shard.is_last_layer:
        pt = inference_state.pop(PROMPT_TOKENS_KEY, None)  # consume: no more hops need it
        if pt:
          self._request_prompt_tokens[request_id] = [int(t) for t in pt]
    try:
      sampler = getattr(self.inference_engine, "infer_sample_tensor", None)
      fuse_sample = shard.is_last_layer and sampler is not None
      with self.tracer.start_span(
        "process_tensor", parent=ctx,
        attributes={"request.id": request_id, "shard.start": shard.start_layer, "shard.end": shard.end_layer},
      ):
        if fuse_sample:
          # Last-layer hop: forward + on-device sampling in one dispatch —
          # only the sampled token int crosses to the host, not the
          # [1, 1, vocab] fp32 logits (VERDICT r1 weak #3).
          token, inference_state = await sampler(
            request_id, shard, tensor, temp=self._temp_for(request_id),
            top_k=self.default_sample_top_k, inference_state=inference_state,
            top_p=self._top_p_for(request_id),
            **self._sampling_kwargs(request_id),
          )
        else:
          result, inference_state = await self.inference_engine.infer_tensor(
            request_id, shard, tensor, inference_state,
            **self._keep_on_device_kwargs(shard, request_id),
          )
      self.metrics.hop_latency.observe((time.perf_counter_ns() - start_ns) / 1e9)
      if fuse_sample:
        await self.process_sampled_token(base_shard, int(token), request_id, inference_state)
      else:
        await self.process_inference_result(base_shard, result, request_id, inference_state)
    except CacheExhausted as e:
      # The KV cache is full: the tokens so far are a valid, truncated
      # completion — end as a normal "length" finish, not an error.
      if DEBUG >= 1:
        print(f"[{request_id}] cache exhausted, finishing as length: {e}")
      await self._finish_as_length(request_id)
    except Exception as e:
      print(f"Error processing tensor for shard {shard}: {e!r}")
      if DEBUG >= 2:
        import traceback
        traceback.print_exc()
      await self._abort_request(request_id, f"tensor hop failed on {self.id} ({shard}): {e!r}")
    finally:
      if DEBUG >= 3:
        print(f"process_tensor elapsed {(time.perf_counter_ns()-start_ns)/1e6:.1f}ms")

  async def _abort_request(self, request_id: str, error: str) -> None:
    """Terminate a request after a hop error: release local state AND tell
    every peer it finished, so mid-ring nodes (which only learn request
    lifecycles from the finished-result broadcast) don't leak bookkeeping or
    KV caches for a request that will never complete. The reference simply
    loses in-flight requests on failure (SURVEY §5); broadcasting a finish
    also unblocks any API client waiting on the token stream. The error
    string rides the broadcast so API nodes surface a real error instead of
    an empty successful completion."""
    self.record_request_error(request_id, error)
    self.metrics.requests_failed_total.inc()
    # Freeze the request's flight timeline BEFORE cleanup churns the ring:
    # watchdog aborts, blown deadlines, and hop errors each become a
    # replayable /v1/debug/flight snapshot instead of one log line.
    self.flight.record("request.aborted", request_id, error=error[:200])
    self.flight.freeze(request_id, reason=error[:200])
    # Watchdog/deadline aborts can fire while the request's driving task is
    # still alive (a hung engine call, a loop awaiting a dead peer): the
    # cancel flag makes any late-completing local work stop at its next
    # boundary instead of resurrecting popped state.
    self._mark_cancelled(request_id)
    tokens, _ = self.buffered_token_output.get(request_id, ([], False))
    self.trigger_on_token_callbacks(request_id, tokens, True)
    try:
      await self.broadcast_result(request_id, tokens, True, error=error)
    except Exception as e:
      # Abort-path broadcast: peers that answered are cleaned up, the dead
      # one is why we're here — local finish below must still run.
      if DEBUG >= 1:
        print(f"[{request_id}] abort broadcast partially failed: {e!r}")
    await self._finish_generation(request_id)

  async def cancel_request(self, request_id: str) -> None:
    """Client-initiated graceful stop (OpenAI stop sequences, disconnects):
    end the request with the tokens produced so far — no error. Takes effect
    between fused chunks / sampled tokens on THIS node (the sampler in
    single-partition serving); a multi-partition ring's other peers finish
    via the resulting broadcast."""
    if request_id not in self.outstanding_requests and request_id not in self.buffered_token_output:
      return  # already finished (or never seen here) — idempotent
    self._mark_cancelled(request_id)
    tokens, _ = self.buffered_token_output.get(request_id, ([], False))
    self.buffered_token_output[request_id] = (tokens, True)
    self.trigger_on_token_callbacks(request_id, tokens, True)
    self._spawn(self.broadcast_result(request_id, [], True, total_len=len(tokens), full_ref=tokens))
    # Final cleanup happens when the driving loop observes the flag at its
    # next boundary (or when the ring's finished broadcast arrives); the
    # flag itself ages out of the bounded LRU, so no cleanup races it.

  def _mark_cancelled(self, request_id: str) -> None:
    self._cancelled[request_id] = None
    self._cancelled.move_to_end(request_id)
    while len(self._cancelled) > 256:
      self._cancelled.popitem(last=False)

  async def _finish_as_length(self, request_id: str) -> None:
    """End a request gracefully with whatever tokens it produced (used when
    the KV cache fills before EOS/cap — the OpenAI 'length' outcome)."""
    tokens, _ = self.buffered_token_output.get(request_id, ([], False))
    self.buffered_token_output[request_id] = (tokens, True)
    self.trigger_on_token_callbacks(request_id, tokens, True)
    try:
      await self.broadcast_result(request_id, tokens, True)
    except Exception as e:
      if DEBUG >= 1:
        print(f"[{request_id}] length-finish broadcast partially failed: {e!r}")
    await self._finish_generation(request_id)

  def record_request_error(self, request_id: str, error: str) -> None:
    """Remember why a request died (bounded; consumed by the API when it
    reports the failure to the client)."""
    self.request_errors[request_id] = error
    while len(self.request_errors) > 256:
      self.request_errors.popitem(last=False)

  async def process_inference_result(self, base_shard: Shard, result: np.ndarray, request_id: str,
                                     inference_state: Optional[dict] = None) -> None:
    """The token-ring decode driver (parity node.py:109-147)."""
    shard = self.get_current_shard(base_shard, request_id=request_id)
    if not shard.is_last_layer:
      # Mid-ring: forward the hidden state (bf16 numpy) to the next partition.
      self.outstanding_requests[request_id] = "waiting"
      await self.forward_tensor(base_shard, result, request_id,
                                self.get_partition_index(offset=1, request_id=request_id),
                                inference_state)
      return

    # Last layer: sample, then continue via the shared token path. Engines
    # with the extras-aware host sampler get the request's sampling config
    # (seed/bias/min_p/logprob recording) — the vision first-token path and
    # fused decode then agree on sampling rules AND logprob entry counts.
    sample_kwargs = {}
    if self._host_sample_accepts_extras():
      n_sampled = len(self.buffered_token_output.get(request_id, ((), 0))[0])
      sample_kwargs = {"request_id": request_id,
                       "sampling": self._request_sampling.get(request_id),
                       "sample_index": n_sampled}
    token = await self.inference_engine.sample(
      result, temp=self._temp_for(request_id), top_k=self.default_sample_top_k,
      top_p=self._top_p_for(request_id), **sample_kwargs,
    )
    await self.process_sampled_token(
      base_shard, int(np.asarray(token).reshape(-1)[0]), request_id, inference_state
    )

  async def process_sampled_token(self, base_shard: Shard, token_int: int, request_id: str,
                                  inference_state: Optional[dict] = None) -> None:
    """Buffer/broadcast a freshly sampled token and either stop (EOS/cap) or
    keep the ring turning. Shared by the sample-on-host path
    (process_inference_result) and the fused on-device sampler."""
    shard = self.get_current_shard(base_shard, request_id=request_id)
    if request_id not in self.buffered_token_output:
      self.buffered_token_output[request_id] = ([], False)
    buffered, _ = self.buffered_token_output[request_id]

    if DEBUG >= 2:
      print(f"[{request_id}] token {token_int} ({len(buffered)+1} so far)")
    if self._ingest_sampled_tokens(request_id, [token_int], buffered, base_shard):
      await self._finish_generation(request_id)
      return

    # Fused fast path: when this single partition owns the whole model, decode
    # K tokens per device dispatch (forward + on-device sampling under one
    # lax.scan, models/generate.py) instead of paying a host round-trip per
    # token. Runs DETACHED so the awaited process_prompt chain returns after
    # the first token and API streaming starts immediately (the per-token
    # path gets the same property from forward_tensor's create_task).
    if self.decode_chunk_size > 1:
      if shard.is_first_layer:
        gen = getattr(self.inference_engine, "generate_chunk", None)
        if gen is not None:
          self._spawn(
            self._fused_decode_loop(base_shard, shard, request_id, buffered, inference_state, gen)
          )
          return
      elif shard.is_last_layer:
        # Multi-partition ring whose every partition is co-located in THIS
        # process: fold the whole chain into one fused executable per chunk
        # (engine.generate_chunk_ring) instead of one hop per partition per
        # token — the ring decodes at the fused rate. The sampler peer (last
        # layer) drives, same as it drives the per-token ring.
        ring = self._ring_fused_gen(base_shard, request_id)
        if ring is not None:
          ring_gen, ring_verify = ring
          self._spawn(
            self._fused_decode_loop(base_shard, shard, request_id, buffered, inference_state,
                                    ring_gen, allow_speculation=False,
                                    ring_verify=ring_verify)
          )
          return

    await self._forward_next_token(base_shard, request_id, buffered, inference_state)

  def _ring_fused_gen(self, base_shard: Shard, request_id: str):
    """A generate_chunk-shaped callable that decodes the WHOLE multi-partition
    ring in fused chunks, or None when the ring doesn't qualify: every
    partition must be served by a ring-fusion-capable engine living in this
    process (self or an in-process peer — the same co-location the
    device-resident hop path keys off), and the request must be a plain one
    (sampling extras keep the per-token path, whose last-layer sampler
    applies them). The chain binds the CURRENT partition table; if membership
    changes mid-generation the engine fails loudly (RequestStateLost) rather
    than decode against remapped shards."""
    if self._request_sampling.get(request_id):
      return None
    ring = getattr(self.inference_engine, "generate_chunk_ring", None)
    if ring is None:
      return None
    chain = self._inprocess_chain(base_shard, request_id)
    if chain is None:
      return None

    async def gen(rid, _shard, prev_token, num_tokens, temp, top_k, top_p=0.0, next_size=None):
      return await ring(rid, chain, prev_token, num_tokens, temp=temp, top_k=top_k,
                        top_p=top_p, next_size=next_size)

    ring_verify_impl = getattr(self.inference_engine, "verify_draft_ring", None)
    verify = None
    if ring_verify_impl is not None:
      async def verify(rid, _shard, prev_token, draft, _impl=ring_verify_impl):
        return await _impl(rid, chain, prev_token, draft)

    return gen, verify

  def _inprocess_chain(self, base_shard: Shard, request_id: Optional[str] = None):
    """The ring-ordered [(engine, shard)] chain when EVERY partition is
    served by a ring-fusion-capable engine in THIS process (self or an
    in-process peer), else None. Shared by the fused-ring dispatch and the
    prompt-token side-channel gating. Ring-mapped requests bind THEIR
    pinned partition table, not the live view."""
    entries = self._ring_entries(request_id)
    if entries is not None:
      node_ids = [n for n, _, _ in entries]
    else:
      try:
        node_ids = [p.node_id for p in self.partitioning_strategy.partition(self.topology)]
      except Exception:
        return None
    if len(node_ids) < 2:
      return None
    chain = []
    for i, node_id in enumerate(node_ids):
      if node_id == self.id:
        eng = self.inference_engine
      else:
        peer = next((p for p in self.peers if p.id() == node_id), None)
        node = getattr(peer, "node", None)  # InProcessPeerHandle only
        eng = getattr(node, "inference_engine", None) if node is not None else None
      if eng is None or not getattr(eng, "supports_ring_fusion", False):
        return None
      chain.append((eng, self.get_current_shard(base_shard, i, request_id=request_id)))
    return chain

  async def _fused_decode_loop(self, base_shard: Shard, shard: Shard, request_id: str,
                               buffered: List[int], inference_state: Optional[dict], gen,
                               allow_speculation: bool = True, ring_verify=None) -> None:
    """Chunked decode until EOS/cap; EOS/max checks happen between chunks and
    surplus tokens after EOS inside a chunk are discarded.
    allow_speculation=False + ring_verify for the fused-RING path: the
    single-shard verify_draft executable must not interleave with
    multi-segment lockstep state, but the ring has its own composite
    verifier (engine.verify_draft_ring) with the same contract."""
    s = self._request_sampling.get(request_id)
    if s and ring_verify is None:
      # A prefill that sampled on the host (multimodal) never bound the
      # request's extras to its decode state — bind them now so the fused
      # chunks apply bias/seed and record logprobs like any text request.
      attach = getattr(self.inference_engine, "attach_sampling", None)
      if attach is not None:
        try:
          await attach(shard, request_id, s, sampled_tokens=tuple(buffered))
        except Exception as e:
          if DEBUG >= 1:
            print(f"[{request_id}] attach_sampling failed: {e!r}")
    # Speculation verifies drafts by plain greedy argmax — requests whose
    # extras RESHAPE the distribution (penalties/bias change even greedy
    # argmax) must not speculate or the verified tokens would ignore them;
    # logprobs requests must not either (the verify path samples nothing,
    # so it would record no logprob entries for accepted drafts). A seed
    # alone is irrelevant at temp==0 (greedy is already deterministic), so
    # seed-only requests keep the speculation fast path.
    # min_p is exempt like seed: speculation only runs at temp==0, where
    # the argmax always satisfies the floor (p_max >= min_p * p_max) — the
    # mask provably cannot change greedy output.
    reshaping = set(self._request_sampling.get(request_id, ())) & {
      "presence_penalty", "frequency_penalty", "logit_bias", "logprobs"}
    spec_wanted = (self.speculate_tokens > 0 and self._temp_for(request_id) == 0
                   and not reshaping)
    if not spec_wanted:
      verify = None
    elif ring_verify is not None:
      verify = ring_verify
    elif allow_speculation:
      verify = getattr(self.inference_engine, "verify_draft", None)
    else:
      verify = None
    # Persistent draft context: prompt + generated tokens, appended as they
    # arrive (never rebuilt — a 32k prompt must not be re-copied per round).
    spec_context = (list(self._request_prompt_tokens.get(request_id, ())) + list(buffered)
                    if verify is not None else [])
    spec_strikes = 0
    try:
      self.outstanding_requests[request_id] = "generating"
      size = self.decode_chunk_size
      while True:
        if request_id in self._cancelled:
          await self._finish_generation(request_id)
          return
        # Never compute far past the request cap: shrink the last chunk to
        # the next power of two covering what the cap still allows.
        limit = self._request_max_tokens.get(request_id, self.max_generate_tokens)
        remaining = max(1, limit - len(buffered))
        if verify is not None:
          # Speculation drafting (greedy only): a draft MODEL when
          # configured (engine.draft_tokens — proposes every round), else
          # prompt-lookup (the continuation of the last n-gram's previous
          # occurrence in prompt+output — model-free, repeat-heavy text
          # only). Either way ONE verify forward yields up to draft+1
          # tokens, each exactly what sequential greedy decode would
          # produce (engine.verify_draft).
          k = min(self.speculate_tokens, remaining)
          drafter = (getattr(self.inference_engine, "draft_tokens", None)
                     if self.draft_model else None)
          if drafter is not None and len(self.outstanding_requests) > 1:
            # Under concurrent load the batcher's shared weight read already
            # amortizes decode; per-request draft forwards would serialize
            # EXTRA executor dispatches — the same measured principle that
            # disables batch-chunk speculation (PERF.md r3: 279 vs 357).
            # Prompt-lookup below stays (its draft is host-side and free).
            drafter = None
          draft = list(await drafter(request_id, spec_context, k)) if drafter else []
          if not draft:
            # Prompt-lookup stays the fallback: the draft model may be
            # unavailable (failed load self-disables it engine-side) or out
            # of cache capacity — n-gram speculation still applies.
            draft = _lookup_draft(spec_context, k)
          if len(draft) >= 2:
            accepted = await verify(request_id, shard, buffered[-1], draft)
            if accepted:
              # Back-off: repeated full rejections (bonus-only returns) mean
              # the text repeats n-grams with divergent continuations — each
              # round would pay a whole verify forward for ONE token, far
              # below the fused-chunk baseline. Stop speculating for this
              # request after two straight misses.
              if len(accepted) == 1:
                spec_strikes += 1
                if spec_strikes >= 2:
                  verify = None
              else:
                spec_strikes = 0
              spec_context.extend(accepted)
              if self._ingest_sampled_tokens(request_id, accepted, buffered, base_shard):
                await self._finish_generation(request_id)
                return
              continue
        this_size = min(size, 1 << (remaining - 1).bit_length())
        # Next-chunk size hint for the engine's speculative dispatch: what
        # THIS loop will ask for next if no EOS lands in this chunk — the
        # ladder's next rung clipped to the cap that will remain. The engine
        # overlaps that chunk with our EOS scan; a misprediction (EOS, cap)
        # is a free rollback on its side.
        rem_after = remaining - this_size
        next_hint = (min(min(size * 2, self.max_decode_chunk_size),
                         1 << (rem_after - 1).bit_length())
                     if rem_after >= 1 else None)
        chunk = await gen(
          request_id, shard, buffered[-1], this_size,
          temp=self._temp_for(request_id), top_k=self.default_sample_top_k,
          top_p=self._top_p_for(request_id), next_size=next_hint,
        )
        if chunk is None:
          # Fast path unavailable (cache nearly full, shard changed): fall
          # back to the per-token ring.
          await self._forward_next_token(base_shard, request_id, buffered, inference_state)
          return
        new_tokens = chunk.reshape(-1).tolist()
        if verify is not None:
          spec_context.extend(int(t) for t in new_tokens)
        if self._ingest_sampled_tokens(request_id, new_tokens, buffered, base_shard):
          await self._finish_generation(request_id)
          return
        size = min(size * 2, self.max_decode_chunk_size)
    except CacheExhausted as e:
      if DEBUG >= 1:
        print(f"[{request_id}] cache exhausted, finishing as length: {e}")
      await self._finish_as_length(request_id)
    except Exception as e:
      print(f"Error in fused decode for [{request_id}]: {e!r}")
      if DEBUG >= 2:
        import traceback
        traceback.print_exc()
      await self._abort_request(request_id, f"fused decode failed on {self.id}: {e!r}")

  async def _forward_next_token(self, base_shard: Shard, request_id: str,
                                buffered: List[int], inference_state: Optional[dict]) -> None:
    # Feed the sampled token back to partition 0 for the next decode step.
    self.outstanding_requests[request_id] = "waiting"
    await self.forward_tensor(
      base_shard, np.asarray([[buffered[-1]]], dtype=np.int64), request_id,
      self.get_partition_index_of_first_layer(), inference_state,
    )

  def _ingest_sampled_tokens(self, request_id: str, new_tokens: List[int], buffered: List[int],
                             base_shard: Optional[Shard] = None) -> bool:
    """Shared per-token accounting for the per-token ring and the fused chunk
    path: append to the request buffer (stopping at EOS or the request cap),
    update metrics/trace, fire callbacks, and broadcast. Returns finished."""
    if request_id in self._cancelled:
      # Tokens computed after a client cancel are discarded; report finished
      # so the driving loop stops at this boundary.
      return True
    eos = self._request_eos.get(request_id)
    if eos is None:
      eos = self._eos_token_ids(base_shard, request_id)
      if eos:
        # Only cache a RESOLVED set: an empty result may mean the tokenizer
        # wasn't ready yet, and freezing that for the request's lifetime
        # would disable EOS detection entirely.
        self._request_eos[request_id] = eos
    limit = self._request_max_tokens.get(request_id, self.max_generate_tokens)
    trace_ctx = self._request_trace_ctx.get(request_id)
    now = time.monotonic()
    self._note_progress(request_id)
    last = self._last_token_time.get(request_id)
    appended = 0
    finished = False
    for t in new_tokens:
      buffered.append(int(t))
      appended += 1
      self.metrics.tokens_total.inc()
      self.tracer.record_token(request_id, trace_ctx)
      if int(t) in eos or len(buffered) >= limit:
        finished = True
        break
    if last is None and appended:
      # First sampled token on this node: the TTFT SLO observation, measured
      # from this node's first touch of the request (prompt/hop arrival).
      started = self._request_started.get(request_id)
      if started is not None:
        self.metrics.ttft.observe(now - started)
    if last is not None and appended:
      self.metrics.token_latency.observe((now - last) / appended)
    self._last_token_time[request_id] = now
    self.buffered_token_output[request_id] = (buffered, finished)
    self.trigger_on_token_callbacks(request_id, buffered, finished)
    # Delta broadcast: only the newly appended tokens ride the wire —
    # O(1) bytes/token instead of the reference's full-list-every-token
    # O(T^2) fan-out (node.py:580-591; SURVEY §2.5 "known-inefficient
    # design to replace"). total_len lets receivers detect gaps and ask for
    # a one-shot full reconciliation (broadcast_result handles the resend).
    delta = buffered[len(buffered) - appended:] if appended else []
    # full_ref is the LIVE buffer object: by the time a gapped peer asks for
    # reconciliation, buffered_token_output may already be popped by
    # _finish_generation — the list object itself stays complete.
    self._spawn(
      self.broadcast_result(request_id, delta, finished, total_len=len(buffered),
                            full_ref=buffered)
    )
    return finished

  async def _finish_generation(self, request_id: str) -> None:
    self.finish_request_state(request_id)
    self.buffered_token_output.pop(request_id, None)  # callbacks/broadcast hold the list
    clear = getattr(self.inference_engine, "clear_request", None)
    if clear is not None:
      await clear(request_id)

  def _temp_for(self, request_id: str) -> float:
    """The request's sampling temperature, falling back to the node default
    (read at SAMPLE time, so a temp that arrived via the tensor
    side-channel after the prompt hop still applies)."""
    return self._request_temp.get(request_id, self.default_sample_temp)

  def _top_p_for(self, request_id: str) -> float:
    """The request's nucleus-sampling threshold; 0.0 (and the OpenAI
    default 1.0, normalised at the API) means disabled."""
    return self._request_top_p.get(request_id, 0.0)

  def _sampling_kwargs(self, request_id: str) -> dict:
    """Extra kwargs for engines whose fused sampler supports the OpenAI
    extras (seed/logit_bias/penalties). Empty for plain requests AND for
    engines whose infer_sample_tensor signature never learned the `sampling`
    kwarg — real signature inspection (cached), so an extras request against
    an older engine degrades to plain sampling instead of TypeError-aborting."""
    s = self._request_sampling.get(request_id)
    if not s:
      return {}
    if self._engine_accepts_sampling is None:
      import inspect
      sampler = getattr(self.inference_engine, "infer_sample_tensor", None)
      try:
        params = inspect.signature(sampler).parameters
        self._engine_accepts_sampling = (
          "sampling" in params
          or any(p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()))
      except (TypeError, ValueError):
        self._engine_accepts_sampling = False
    return {"sampling": s} if self._engine_accepts_sampling else {}

  def _host_sample_accepts_extras(self) -> bool:
    """Does engine.sample accept request_id/sampling? Same cached signature
    inspection as _sampling_kwargs, for the host sampling path."""
    if getattr(self, "_host_sample_extras", None) is None:
      import inspect
      try:
        params = inspect.signature(self.inference_engine.sample).parameters
        self._host_sample_extras = (
          "sampling" in params
          or any(p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()))
      except (TypeError, ValueError):
        self._host_sample_extras = False
    return self._host_sample_extras

  def pop_request_logprobs(self, request_id: str, n: Optional[int] = None) -> Optional[list]:
    """Drain the engine's recorded logprob entries for a request (OpenAI
    `logprobs`). None when the local engine recorded none — plain requests,
    engines without the feature, or rings where a REMOTE node samples (the
    token broadcast carries ids only; logprob reporting requires the API
    node to host the sampling shard)."""
    pop = getattr(self.inference_engine, "pop_logprobs", None)
    return pop(request_id, n) if pop is not None else None

  def _clamp_max_tokens(self, cap: Any) -> int:
    return max(1, min(int(cap), self.max_generate_tokens))

  def _eos_token_ids(self, base_shard: Optional[Shard] = None,
                     request_id: Optional[str] = None) -> Tuple[int, ...]:
    """EOS ids for the REQUEST's model. With per-model engine contexts, the
    engine's active tokenizer/cfg may belong to a different in-flight model —
    resolve per shard when the engine supports it, never from whichever
    model happens to be active. Ring-mapped requests resolve their PINNED
    shard (the engine context key), not the live view's."""
    per_shard = getattr(self.inference_engine, "eos_token_ids_for", None)
    if base_shard is not None and per_shard is not None:
      try:
        ids = per_shard(self.get_current_shard(base_shard, request_id=request_id))
        # Empty means "context not resident / tokenizer unresolved", not
        # "this model has no EOS" — fall through to the engine-level lookup
        # rather than silently disabling EOS detection.
        if ids:
          return ids
      except Exception as e:
        # Fall through to the engine-level tokenizer lookup below.
        if DEBUG >= 2:
          print(f"per-shard EOS lookup failed ({e!r}); using engine tokenizer")
    tokenizer = getattr(self.inference_engine, "tokenizer", None)
    eos = getattr(tokenizer, "eos_token_id", None) if tokenizer else None
    cfg = getattr(self.inference_engine, "cfg", None)
    from_cfg = tuple(getattr(cfg, "eos_token_ids", ()) or ()) if cfg else ()
    return tuple(e for e in ((eos,) if eos is not None else ()) + from_cfg)

  # -------------------------------------------------------------- routing

  def _set_ring_map(self, request_id: str, ring_map) -> None:
    """Record a request's pinned partition map (bounded LRU — an abandoned
    request must not leak its row forever; finish_request_state pops it on
    the normal path)."""
    rows = [(str(n), int(s), int(e)) for n, s, e in ring_map]
    self._request_ring_map[request_id] = rows
    self._request_ring_map.move_to_end(request_id)
    while len(self._request_ring_map) > 512:
      self._request_ring_map.popitem(last=False)

  def _ring_entries(self, request_id: Optional[str]):
    """The request's pinned [node_id, start, end] rows, or None when the
    request predates the map (old peer on the wire) / isn't ring-routed.
    Reads refresh the LRU: a long-lived streaming request must not lose its
    map to 512 newer requests and silently fall back to live-view routing."""
    if not request_id:
      return None
    rows = self._request_ring_map.get(request_id)
    if rows is not None:
      self._request_ring_map.move_to_end(request_id)
    return rows

  def _pin_ring_map(self, base_shard: Shard, request_id: str) -> None:
    """Originate a request's routing epoch from THIS node's current view.
    Called exactly once, by the node that first accepts the request."""
    if request_id in self._request_ring_map or not self.partitioning_strategy:
      return
    partitions = self.partitioning_strategy.partition(self.topology)
    shards = map_partitions_to_shards(partitions, base_shard.n_layers, base_shard.model_id)
    self._set_ring_map(request_id, [
      (p.node_id, s.start_layer, s.end_layer) for p, s in zip(partitions, shards)
    ])

  def get_partition_index(self, offset: int = 0, request_id: Optional[str] = None) -> int:
    entries = self._ring_entries(request_id)
    if entries is not None:
      current = next((i for i, (n, _, _) in enumerate(entries) if n == self.id), None)
      if current is None:
        raise ValueError(f"Node {self.id} is not in request {request_id}'s ring map")
      return (current + offset) % len(entries)
    if not self.partitioning_strategy:
      return 0
    partitions = self.partitioning_strategy.partition(self.topology)
    current = next((i for i, p in enumerate(partitions) if p.node_id == self.id), None)
    if current is None:
      raise ValueError(f"No partition found for node {self.id}")
    return (current + offset) % len(partitions)

  def get_partition_index_of_first_layer(self) -> int:
    # map_partitions_to_shards assigns layer 0 to partitions[0] by
    # construction, so the first-layer owner is always ring index 0 — in the
    # live view AND in any pinned ring map (rows preserve partition order).
    return 0

  def get_current_shard(self, base_shard: Shard, index: Optional[int] = None,
                        request_id: Optional[str] = None) -> Shard:
    entries = self._ring_entries(request_id)
    if entries is not None:
      if index is None:
        index = self.get_partition_index(request_id=request_id)
      _, start, end = entries[index]
      return Shard(base_shard.model_id, start, end, base_shard.n_layers)
    if index is None:
      index = self.get_partition_index()
    partitions = self.partitioning_strategy.partition(self.topology)
    shards = map_partitions_to_shards(partitions, base_shard.n_layers, base_shard.model_id)
    return shards[index]

  async def _peer_by_id(self, target_id: str):
    """Resolve a hop's peer handle, healing transient peer-set lag: the
    peer set is reconciled on a background cadence, and a hop can race a
    window where discovery knows the peer but self.peers briefly doesn't
    (a replaced handle whose connect timed out once, an admission that
    finished after the last reconcile). One on-demand reconcile turns that
    race into a served request instead of an abort; a peer that is GONE
    still fails (update_peers can't resurrect it) and keeps the abort
    semantics."""
    peer = next((p for p in self.peers if p.id() == target_id), None)
    if peer is not None:
      return peer
    try:
      await self.update_peers()
    except Exception as e:
      if DEBUG >= 2:
        print(f"on-demand peer reconcile failed: {e!r}")
    return next((p for p in self.peers if p.id() == target_id), None)

  def _ring_target_id(self, target_index: int, request_id: Optional[str]) -> str:
    entries = self._ring_entries(request_id)
    if entries is not None:
      return entries[target_index][0]
    return self.partitioning_strategy.partition(self.topology)[target_index].node_id

  async def forward_prompt(self, base_shard: Shard, prompt: str, request_id: str, target_index: int,
                           images: Optional[List[np.ndarray]] = None) -> None:
    if DEBUG >= 1:
      print(f"Forwarding prompt [{request_id}] to partition {target_index}")
    target_id = self._ring_target_id(target_index, request_id)
    next_shard = self.get_current_shard(base_shard, target_index, request_id=request_id)
    if target_id == self.id:
      await self._process_prompt(base_shard, prompt, request_id, images)
      return
    peer = await self._peer_by_id(target_id)
    if peer is None:
      raise ValueError(f"Peer for {target_index} ({target_id}) not found")
    ctx = self._request_trace_ctx.get(request_id)
    dl = self._request_deadline.get(request_id)
    await peer.send_prompt(next_shard, prompt, request_id,
                           traceparent=ctx.traceparent() if ctx else None,
                           max_tokens=self._request_max_tokens.get(request_id),
                           images=images,
                           temperature=self._request_temp.get(request_id),
                           top_p=self._request_top_p.get(request_id),
                           ring_map=self._ring_entries(request_id),
                           deadline=max(0.0, dl - time.monotonic()) if dl is not None else None)

  def _keep_on_device_kwargs(self, shard: Shard, request_id: Optional[str] = None) -> dict:
    """Engine kwargs for a mid-ring hop: request device-resident output when
    the engine supports it AND the next partition is co-located (self or an
    in-process peer — the fast path that keeps hidden states in HBM across
    the hop, VERDICT r2 #3). One partition computation, not three: this sits
    on the per-token hot path it exists to optimize."""
    if shard.is_last_layer or not getattr(self.inference_engine, "supports_device_io", False):
      return {}
    try:
      target_id = self._ring_target_id(
        self.get_partition_index(offset=1, request_id=request_id), request_id)
    except Exception:
      return {}
    if target_id == self.id:
      return {"keep_on_device": True}
    peer = next((p for p in self.peers if p.id() == target_id), None)
    if peer is not None and getattr(peer, "accepts_device_arrays", False):
      return {"keep_on_device": True}
    return {}

  async def forward_tensor(self, base_shard: Shard, tensor, request_id: str, target_index: int,
                           inference_state: Optional[dict] = None) -> None:
    target_id = self._ring_target_id(target_index, request_id)
    next_shard = self.get_current_shard(base_shard, target_index, request_id=request_id)
    # Inject the trace context so the receiving peer's hop span joins this
    # request's trace (rides the existing inference_state side-channel).
    ctx = self._request_trace_ctx.get(request_id)
    if ctx is not None:
      inference_state = {**(inference_state or {}), TRACEPARENT_KEY: ctx.traceparent()}
    ring_rows = self._ring_entries(request_id)
    if ring_rows is not None:
      inference_state = {**(inference_state or {}), RING_MAP_KEY: ring_rows}
    cap = self._request_max_tokens.get(request_id)
    if cap is not None:
      inference_state = {**(inference_state or {}), MAX_TOKENS_KEY: cap}
    t = self._request_temp.get(request_id)
    if t is not None:
      inference_state = {**(inference_state or {}), TEMP_KEY: t}
    p = self._request_top_p.get(request_id)
    if p is not None:
      inference_state = {**(inference_state or {}), TOP_P_KEY: p}
    s = self._request_sampling.get(request_id)
    if s is not None:
      inference_state = {**(inference_state or {}), SAMPLING_KEY: s}
    dl = self._request_deadline.get(request_id)
    if dl is not None:
      inference_state = {**(inference_state or {}), DEADLINE_KEY: max(0.0, dl - time.monotonic())}
    if target_id == self.id:
      # Schedule rather than await: a direct call would grow one coroutine
      # chain per token and blow the recursion limit on long generations.
      self._spawn(self.process_tensor(base_shard, tensor, request_id, inference_state))
      return
    peer = await self._peer_by_id(target_id)
    if peer is None:
      raise ValueError(f"Peer for {target_index} ({target_id}) not found")
    if not getattr(peer, "accepts_device_arrays", False) and not isinstance(tensor, np.ndarray):
      # Cross-host hop: the device array materialises to numpy HERE and only
      # here — the wire/codec path stays numpy-typed.
      tensor = np.asarray(tensor)
    await peer.send_tensor(next_shard, tensor, request_id, inference_state)

  # ------------------------------------------------------------- training

  async def enqueue_example(self, base_shard: Shard, example: np.ndarray, target: np.ndarray,
                            length: np.ndarray, train: bool = False,
                            request_id: Optional[str] = None) -> Tuple[float, Optional[np.ndarray]]:
    """Route an example to the partition-0 owner (parity node.py:210-228).
    Pins the example's ring map (RING_MAP_KEY) like a serving request: every
    peer must run the layer range THIS node's view assigns, or a peer whose
    gossip lags processes the example against the wrong partitioning — the
    observed failure was a peer running the FULL model for an example the
    origin had pipelined, silently applying its optimizer update to an
    orphaned context."""
    if request_id is None:
      request_id = str(uuid.uuid4())
    self._pin_ring_map(base_shard, request_id)
    shard = self.get_current_shard(base_shard, request_id=request_id)
    if shard.is_first_layer:
      return await self.process_example(base_shard, example, target, length, train, request_id)
    index = self.get_partition_index_of_first_layer()
    target_id = self._ring_target_id(index, request_id)
    peer = await self._peer_by_id(target_id)
    if peer is None:
      raise ValueError(f"No peer for first-layer partition {index}")
    try:
      result = await peer.send_example(
        self.get_current_shard(base_shard, index, request_id=request_id),
        example, target, length, train, request_id,
        ring_map=self._ring_entries(request_id))
    finally:
      # Training is strictly request/response: the pinned row is dead once
      # the example returns, and leaving it would churn the bounded LRU
      # under long training loops (evicting live SERVING requests' maps).
      self._request_ring_map.pop(request_id, None)
    if result is None:
      raise RuntimeError(f"Peer {target_id} returned no loss for example {request_id}")
    return result

  async def process_example(self, base_shard: Shard, example: np.ndarray, target: np.ndarray,
                            length: np.ndarray, train: bool = False,
                            request_id: Optional[str] = None,
                            ring_map: Optional[list] = None) -> Tuple[float, Optional[np.ndarray]]:
    """Run this shard's slice of a training/eval example; recurse down the
    ring and chain gradients back up (parity node.py:254-345)."""
    if request_id is None:
      request_id = str(uuid.uuid4())
    if ring_map and request_id not in self._request_ring_map:
      self._set_ring_map(request_id, ring_map)
    shard = self.get_current_shard(base_shard, request_id=request_id)
    start_ns = time.perf_counter_ns()
    status_kind = "train_example" if train else "eval_example"
    self._spawn(self.broadcast_opaque_status(request_id, json.dumps({
      "type": "node_status", "node_id": self.id, "status": f"start_{status_kind}",
      "request_id": request_id,
    })))
    try:
      if train:
        loss, grads = await self.inference_engine.train_example(
          request_id, shard, example, target, length,
          forward_fn=self._forward_example_fn(base_shard, request_id),
        )
        return loss, grads
      else:
        loss = await self.inference_engine.evaluate_example(
          request_id, shard, example, target, length,
          forward_fn=self._forward_example_fn(base_shard, request_id),
        )
        return loss, None
    finally:
      self._request_ring_map.pop(request_id, None)  # request/response: row is dead
      self._spawn(self.broadcast_opaque_status(request_id, json.dumps({
        "type": "node_status", "node_id": self.id, "status": f"end_{status_kind}",
        "request_id": request_id, "elapsed_time_ns": time.perf_counter_ns() - start_ns,
      })))

  def _forward_example_fn(self, base_shard: Shard, request_id: str):
    """Downstream hop for pipelined training: ships activations to the next
    partition, returns (loss, grad_wrt_activations)."""
    async def forward(activations: np.ndarray, target: np.ndarray, length: np.ndarray, train: bool):
      next_index = self.get_partition_index(offset=1, request_id=request_id)
      target_id = self._ring_target_id(next_index, request_id)
      next_shard = self.get_current_shard(base_shard, next_index, request_id=request_id)
      if target_id == self.id:
        return await self.process_example(base_shard, activations, target, length, train, request_id)
      peer = await self._peer_by_id(target_id)
      if peer is None:
        raise ValueError(f"No peer for partition {next_index}")
      result = await peer.send_example(next_shard, activations, target, length, train, request_id,
                                       ring_map=self._ring_entries(request_id))
      if result is None:
        raise RuntimeError(f"Peer {target_id} returned no loss for example {request_id}")
      return result
    return forward

  async def _resume_local(self, base_shard: Shard, path: str) -> None:
    try:
      shard = self.get_current_shard(base_shard)
      await self.inference_engine.load_checkpoint(shard, path)
      if DEBUG >= 1:
        print(f"Resumed {shard} from {path}")
    except Exception as e:
      print(f"Resume of {base_shard.model_id} from {path} failed on {self.id}: {e!r}")

  async def coordinate_resume(self, base_shard: Shard, path: str) -> None:
    """Restore a checkpoint across the WHOLE ring: load the local layer range
    and broadcast a resume_checkpoint status so every peer loads its own
    (the per-shard save files share one directory — coordinate_save naming).
    Completes the reference's parsed-but-dead --resume-checkpoint flag
    (ref main.py:82; engine leaf was a no-op, inference_engine.py:31-35)."""
    await self._resume_local(base_shard, path)
    await self.broadcast_opaque_status("", json.dumps({
      "type": "resume_checkpoint", "node_id": self.id,
      "base_shard": base_shard.to_dict(), "path": path,
    }))

  async def coordinate_save(self, base_shard: Shard, iteration: int, destination: str) -> None:
    """Ask every peer('s engine) to save its shard (parity node.py:230-252)."""
    shard = self.get_current_shard(base_shard)
    model = base_shard.model_id
    sid = f"{shard.start_layer}-{shard.end_layer}"
    self.checkpoints.setdefault(model, {})
    if self.checkpoints[model].get(sid) == iteration:
      return
    self.checkpoints[model][sid] = iteration
    path = f"{destination}/{model}/{sid}-{iteration}.safetensors"
    await self.inference_engine.save_checkpoint(shard, path)
    if DEBUG >= 1:
      print(f"Saved checkpoint {path}")

  # ------------------------------------------------------------- topology

  async def update_peers(self, wait_for_peers: int = 0) -> bool:
    """Reconcile the peer set against discovery (parity node.py:462-511).
    Serialized: the read-modify-write of self.peers spans awaits (connects/
    disconnects), and callers now include on-demand hop-time reconciles
    (_peer_by_id) racing the periodic loop — unsynchronized runs would
    clobber each other's peer-set assignment."""
    async with self._update_peers_lock:
      return await self._update_peers_locked(wait_for_peers)

  async def _update_peers_locked(self, wait_for_peers: int = 0) -> bool:
    next_peers = await self.discovery.discover_peers(wait_for_peers)
    # Health-evicted peers stay out for their cooldown even when discovery
    # still lists them (its liveness view can lag a death by many seconds).
    next_peers = [p for p in next_peers if not self._is_evicted(p.id())]
    current_ids = {p.id() for p in self.peers}
    next_ids = {p.id() for p in next_peers}
    peers_added = [p for p in next_peers if p.id() not in current_ids]
    peers_removed = [p for p in self.peers if p.id() not in next_ids]
    # Keep known peers, but ADOPT discovery's replacement handle when the
    # peer's address changed (re-admitted via a better NIC): the old handle
    # was gracefully disconnected by discovery and reconnecting it would
    # dial the address that just lost. Adopted handles lazy-connect on
    # first call.
    by_id = {p.id(): p for p in next_peers}
    peers_kept = []
    for p in self.peers:
      if p.id() not in next_ids:
        continue
      replacement = by_id[p.id()]
      if replacement is not p and replacement.addr() != p.addr():
        if DEBUG >= 1:
          print(f"Peer {p.id()} address changed {p.addr()} -> {replacement.addr()}; adopting new handle")
        peers_kept.append(replacement)
      else:
        peers_kept.append(p)

    async def _connect(peer):
      try:
        await asyncio.wait_for(peer.connect(), timeout=5.0)
        return True
      except Exception as e:
        if DEBUG >= 1:
          print(f"Failed to connect {peer.id()}: {e!r}")
        return False

    async def _disconnect(peer):
      try:
        # Graceful: eviction can race an in-flight RPC on a peer that is
        # flapping rather than dead; cancelling it mid-call would abort a
        # healthy request. Returns immediately (the drain runs detached),
        # so no timeout is needed here.
        await peer.disconnect(grace=600.0)
      except Exception as e:
        if DEBUG >= 2:
          print(f"Failed to disconnect {peer.id()}: {e!r}")

    connected = await asyncio.gather(*(_connect(p) for p in peers_added))
    await asyncio.gather(*(_disconnect(p) for p in peers_removed))
    # Re-filter at assignment: an eviction can land during the awaits above
    # (the health monitor doesn't hold this lock) and must not be undone by
    # this read-modify-write completing with its stale snapshot.
    self.peers = [p for p in peers_kept + [p for p, ok in zip(peers_added, connected) if ok]
                  if not self._is_evicted(p.id())]
    for p in self.peers:
      # Hand each peer handle this node's flight recorder so hop.send events
      # (with their dedup seq ids) land in the SENDER's timeline, and the
      # clock collector so hop sends carry this node's wall stamp.
      p.flight = self.flight
      p.clock = self.clock
    self.metrics.peers.set(len(self.peers))
    return bool(peers_added or peers_removed)

  async def periodic_topology_collection(self, interval: float) -> None:
    while True:
      await asyncio.sleep(interval)
      try:
        changed = await self.update_peers()
        if changed:
          await self.collect_topology(set())
          await self.select_best_inference_engine()
        if self.peers:
          # Piggyback the cluster metrics rollup on the topology cadence:
          # a compact summary per tick keeps every peer's
          # /v1/cluster/metrics view fresh without a new RPC surface.
          await self.broadcast_opaque_status("", json.dumps({
            "type": "node_metrics", "node_id": self.id,
            "metrics": self.metrics_summary(),
          }))
      except Exception as e:
        if DEBUG >= 1:
          print(f"Topology collection error: {e!r}")

  async def collect_topology(self, visited: set, max_depth: int = 4) -> Topology:
    """Visited-set BFS gossip crawl (parity node.py:533-566)."""
    prev_visited = set(visited)
    next_topology = Topology()
    next_topology.update_node(self.id, self.device_capabilities)
    visited.add(self.id)
    visited.update(p.id() for p in self.peers)

    for peer in self.peers:
      next_topology.update_node(peer.id(), peer.device_capabilities())
      next_topology.add_edge(self.id, peer.id(), peer.description())
      if peer.id() in prev_visited or max_depth <= 0:
        continue  # someone up the crawl already asked this peer
      try:
        other = await asyncio.wait_for(peer.collect_topology(set(visited), max_depth - 1), timeout=5.0)
        visited.update(other.nodes.keys())
        # Origin-filtered merge takes only the peer's OWN edges/caps (a stale
        # or malicious peer cannot rewrite the rest of the graph); transitive
        # nodes it learned about are added if we don't know them yet.
        next_topology.merge(peer.id(), other)
        for node_id, caps in other.nodes.items():
          if node_id not in next_topology.nodes:
            next_topology.update_node(node_id, caps)
      except Exception as e:
        if DEBUG >= 2:
          print(f"collect_topology from {peer.id()} failed: {e!r}")

    next_topology.active_node_id = self.topology.active_node_id
    self.topology = next_topology
    if self.topology_viz is not None:
      try:
        self.topology_viz.update_visualization(self.topology, self.partitioning_strategy.partition(self.topology), self.id)
      except Exception as e:
        # Viz is cosmetic; a TUI paint error must never break topology
        # collection — but don't hide it from whoever is debugging the TUI.
        if DEBUG >= 2:
          print(f"topology viz update failed: {e!r}")
    return next_topology

  async def select_best_inference_engine(self) -> None:
    """Broadcast which engines this node supports so the cluster can settle
    on an intersection (parity node.py:513-518)."""
    supported = [type(self.inference_engine).__name__]
    await self.broadcast_opaque_status("", json.dumps({
      "type": "supported_inference_engines", "node_id": self.id, "engines": supported,
    }))

  def get_supported_models_for_cluster(self) -> List[str]:
    pools = self.topology_inference_engines_pool or [[type(self.inference_engine).__name__]]
    return get_supported_models(pools)

  # ------------------------------------------------------------ broadcast

  def finish_request_state(self, request_id: str) -> None:
    """Release all per-request bookkeeping (idempotent). Runs on the sampler
    when a request finishes or errors, and on every other peer when the
    finished-result broadcast arrives — so mid-ring nodes don't leak
    outstanding/trace state for requests whose end they never see locally."""
    self.outstanding_requests.pop(request_id, None)
    self.metrics.active_requests.set(len(self.outstanding_requests))
    self.tracer.finish_request(request_id)
    started = self._request_started.pop(request_id, None)
    if started is not None:
      elapsed = time.monotonic() - started
      self.metrics.request_latency.observe(elapsed)
      self.flight.record("request.finished", request_id, secs=round(elapsed, 4))
    ctx = self._request_trace_ctx.pop(request_id, None)
    if ctx is not None and ctx.sampled and self.tracer.enabled and self.peers:
      # Cluster trace rollup: flush THIS node's shard of the request's
      # spans over the status bus, so any node's /v1/traces returns the
      # whole ring's trace. The ctx pop above makes this once-per-request
      # (finish_request_state is idempotent). Spawn guarded: harness code
      # calls this without a running loop — rollup is best-effort there.
      try:
        self._spawn(self._flush_trace_spans(request_id, ctx.trace_id))
      except RuntimeError:
        pass  # no running event loop (sync harness/test call): skip rollup
    was_origin = request_id in self._anatomy_origin
    self._anatomy_origin.pop(request_id, None)
    if ctx is not None and was_origin and self.anatomy.enabled and self.tracer.enabled:
      # Origin-only, once per request (the ctx pop above + the origin-set
      # pop here gate it). Delayed so remote span shards land first.
      try:
        self._spawn(self._assemble_anatomy(request_id, ctx.trace_id))
      except RuntimeError:
        pass  # no running event loop: anatomy is best-effort in harnesses
    self._last_token_time.pop(request_id, None)
    self._request_max_tokens.pop(request_id, None)
    self._request_temp.pop(request_id, None)
    self._request_top_p.pop(request_id, None)
    self._request_sampling.pop(request_id, None)
    self._request_eos.pop(request_id, None)
    self._request_prompt_tokens.pop(request_id, None)
    self._request_ring_map.pop(request_id, None)
    self._request_deadline.pop(request_id, None)
    self._last_progress.pop(request_id, None)
    self._stall_deferred.discard(request_id)
    # _hop_seen rows deliberately OUTLIVE the request (they age out of the
    # bounded LRU instead): a slow retry can land after the request
    # finished, and admitting it as fresh would resurrect per-request state
    # for a dead request.

  def trigger_on_token_callbacks(self, request_id: str, tokens: List[int], is_finished: bool) -> None:
    self.on_token.trigger_all(request_id, tokens, is_finished)

  async def broadcast_result(self, request_id: str, result: List[int], is_finished: bool,
                             error: Optional[str] = None, total_len: Optional[int] = None,
                             full_ref: Optional[List[int]] = None) -> None:
    """Fan the (delta) token payload out to every peer. A peer whose ack
    reports a gap (it missed an earlier broadcast — joined late, dropped an
    RPC) gets a full-list reconciliation send (retried once: for a finished
    request this second RPC is the peer's only chance to learn the end);
    steady state stays O(1) bytes per token. `full_ref` is the sender's live
    token buffer — read at reconciliation time, NOT via buffered_token_output
    (the sampler pops that entry the moment the request finishes)."""
    async def send(peer):
      try:
        ack = await asyncio.wait_for(
          peer.send_result(request_id, result, is_finished, error=error, total_len=total_len),
          timeout=15.0,
        )
        if total_len is not None and isinstance(ack, dict) and ack.get("applied") is False:
          full = list(full_ref) if full_ref is not None else (
            self.buffered_token_output.get(request_id, (list(result), is_finished))[0]
          )
          for attempt in (1, 2):
            try:
              await asyncio.wait_for(
                peer.send_result(request_id, full, is_finished, error=error,
                                 total_len=len(full)),
                timeout=15.0,
              )
              break
            except Exception:
              if attempt == 2:
                raise
      except Exception as e:
        if DEBUG >= 2:
          print(f"broadcast_result to {peer.id()} failed: {e!r}")
    await asyncio.gather(*(send(p) for p in self.peers), return_exceptions=True)

  async def ingest_remote_result(self, request_id: str, tokens: List[int],
                                 total_len: Optional[int], is_finished: bool,
                                 error: Optional[str] = None) -> Tuple[bool, int]:
    """Receiver side of the delta token broadcast: reconcile the delta into
    this peer's buffer. Returns (applied, have) for the sender's ack — a gap
    (missed broadcast) reports applied=False so the sender re-sends the full
    list. total_len=None means `tokens` IS the full list (legacy/abort
    sends).

    Ordering robustness (each broadcast is an independent task, so unary
    RPCs to the same peer can land out of order): a send whose total_len is
    not ahead of what we hold is STALE and ignored (monotonic guard — a
    delayed early delta must never truncate newer state), and anything
    arriving after the finish was applied is dropped outright (a straggler
    must not resurrect per-request state or fire post-finish callbacks)."""
    if request_id in self._finished_results:
      return True, 0  # straggler after finish: drop
    buffered, _ = self.buffered_token_output.get(request_id, ([], False))
    have = len(buffered)
    if is_finished and not tokens:
      # A mid-ring abort/exhaustion broadcast carries no token payload (only
      # the sampler buffers tokens); fall back to whatever this peer knows so
      # listeners aren't handed an empty completion.
      merged = buffered
    elif total_len is not None and total_len <= have and not is_finished and not error:
      return True, have  # stale reorder: newer state already held
    elif total_len is None or total_len == len(tokens):
      merged = list(tokens)  # full list (legacy send or reconciliation)
    else:
      start = total_len - len(tokens)
      if have >= start:
        merged = buffered[:start] + list(tokens)  # contiguous (or finish replay)
      else:
        # Gap: we never saw tokens [have, start). Don't hand listeners a
        # sequence with a hole — ask for reconciliation. Record the error
        # NOW though: its delivery must not depend on the second RPC.
        if error:
          self.record_request_error(request_id, error)
        return False, have
    if error:
      # Record before triggering so API consumers see the cause when the
      # finished callback lands.
      self.record_request_error(request_id, error)
    # Applied deltas are progress for THIS peer's stall watchdog: mid-ring
    # nodes see no hops during a healthy generation — the sampler's token
    # broadcasts are their only heartbeat.
    self._note_progress(request_id)
    self.buffered_token_output[request_id] = (merged, is_finished)
    self.trigger_on_token_callbacks(request_id, merged, is_finished)
    if is_finished:
      # The finished broadcast is how non-sampler peers learn a request
      # ended; run the same cleanup the sampler runs (bookkeeping + the
      # engine's resident KV cache). Remember the id (bounded) so delayed
      # stragglers can't resurrect the request. Mark cancelled too: if THIS
      # peer is the sampler with a decode loop still running (an API peer
      # cancelled on a stop sequence), the loop must stop at its next
      # boundary, not run to the cap re-creating popped request state.
      self._mark_cancelled(request_id)
      self._finished_results[request_id] = None
      while len(self._finished_results) > 512:
        self._finished_results.popitem(last=False)
      await self._finish_generation(request_id)
    return True, len(merged)

  async def _flush_trace_spans(self, request_id: str, trace_id: str) -> None:
    """Cluster trace rollup (sender side): ship this node's finished spans
    for one trace over the opaque-status bus. Export filters by node.id, so
    spans previously ingested FROM peers are never re-broadcast (no echo
    amplification); receivers dedup by span id anyway. The short sleep lets
    the spans enclosing the finish (hop span, prompt root) close first."""
    await asyncio.sleep(0.05)
    spans = self.tracer.export(trace_id=trace_id, node_id=self.id)
    if not spans:
      return
    await self.broadcast_opaque_status(request_id, json.dumps({
      "type": "trace_spans", "node_id": self.id, "request_id": request_id,
      "trace_id": trace_id, "spans": spans,
    }))

  def _peer_hop_rtts(self) -> Dict[str, float]:
    """This node's hop-RTT EWMA seconds per peer (sender-side view) — the
    transit bound the skew estimator's one-way edges need."""
    out: Dict[str, float] = {}
    for p in self.peers:
      ewma = getattr(p, "hop_rtt", None)
      v = ewma.value() if ewma is not None else None
      if v is not None:
        out[p.id()] = round(v, 6)
    return out

  def ring_offsets_view(self) -> Dict[str, dict]:
    """Every reachable node's clock offset relative to THIS node, from the
    local skew estimator plus each peer's `clock` summary off the status
    bus (orchestration/anatomy.ring_offsets)."""
    clocks: Dict[str, dict] = {self.id: self.clock.deltas()}
    rtts: Dict[str, Dict[str, float]] = {self.id: self._peer_hop_rtts()}
    for nid, summary in self.peer_metrics.items():
      if self.peer_metrics_stale(nid):
        # Same rule as the cluster metrics aggregate: a dead/wedged peer's
        # last clock window is history, not signal — solving offsets from
        # it would silently freeze the correction at the moment it died.
        continue
      clk = summary.get("clock") if isinstance(summary, dict) else None
      if isinstance(clk, dict):
        clocks[nid] = clk.get("deltas") or {}
        if isinstance(clk.get("hop_rtt_s"), dict):
          rtts[nid] = clk["hop_rtt_s"]
    return ring_offsets(self.id, clocks, rtts)

  async def _assemble_anatomy(self, request_id: str, trace_id: str) -> None:
    """Origin-side breakdown assembly for one finished request: wait a beat
    for remote span shards to arrive over the status bus, re-base the
    assembled trace onto this clock, and reservoir the stage breakdown."""
    await asyncio.sleep(self._anatomy_delay_s)
    try:
      spans = self.tracer.export(trace_id=trace_id)
      if not spans:
        return
      offsets = self.ring_offsets_view()
      # Off the event loop: a long generation's trace holds thousands of
      # spans and the sweep is quadratic-ish in them — blocking decode for
      # every in-flight request at each finish is not acceptable.
      breakdown = await asyncio.get_running_loop().run_in_executor(
        None, extract_breakdown, spans, offsets, request_id, trace_id)
      if breakdown is None:
        return
      self.anatomy.add(breakdown)
      self.flight.record(
        "anatomy.breakdown", request_id, e2e_s=breakdown["e2e_s"],
        stages=len(breakdown["stages"]),
        unattributed_s=breakdown["stages"]["unattributed"]["secs"])
    except Exception as e:
      if DEBUG >= 1:
        print(f"[{request_id}] anatomy assembly failed: {e!r}")

  def spool_flight(self, reason: str = "") -> Optional[str]:
    """Post-mortem spool: dump the flight ring + frozen snapshots to
    XOT_FLIGHT_DUMP_DIR (no-op when unset) so a SIGTERM'd node's evidence
    survives the process. Called from the main-loop signal handler."""
    dump_dir = knobs.get_str("XOT_FLIGHT_DUMP_DIR")
    if not dump_dir:
      return None
    return self.flight.dump_to(dump_dir, reason=reason)

  def metrics_summary(self) -> dict:
    """This node's compact metric summary (counters + histogram sum/count)
    for the cluster rollup — what rides the status bus and what
    /v1/cluster/metrics serves per node."""
    summary = self.metrics.summary()
    summary["node_id"] = self.id
    summary["ts"] = time.time()
    if self.clock.enabled:
      # Clock-skew compact: this node's received one-way deltas per sender
      # plus its sender-side hop RTTs — what lets the ORIGIN solve the
      # whole ring's offsets (anatomy.ring_offsets) from one rollup.
      summary["clock"] = {"deltas": self.clock.deltas(),
                          "hop_rtt_s": self._peer_hop_rtts()}
    # Roofline-attribution compact (engines that expose one): rides the
    # same status-bus broadcast, so /v1/perf on any node rolls up the ring.
    perf_fn = getattr(self.inference_engine, "perf_compact", None)
    perf = perf_fn() if callable(perf_fn) else None
    if perf is not None:
      summary["perf"] = perf
    # Alert compact (active + recent + degraded peers): rides the same
    # broadcast so ONE /v1/alerts scrape on any node sees the whole ring's
    # firing alerts with their localization verdicts.
    if self.alerts.enabled:
      summary["alerts"] = self.alerts.compact()
    # Admission compact (inflight/queued/est-wait): only while the gate is
    # enabled — defaults-off must add no keys to the wire.
    if self.admission.enabled:
      summary["admission"] = self.admission.compact()
    # History compact (trailing gauge means): what ring peers' drift
    # sentinels median against. Only while enabled — XOT_HISTORY=0 must
    # add no keys to the wire.
    if self.history.enabled:
      summary["history"] = self.history.compact()
    return summary

  async def prefetch_prompt(self, base_shard: Shard, prompt: str) -> bool:
    """PRESERVE-style anticipatory KV prefetch (arXiv 2501.08192): start the
    engine's host-to-HBM prefix restore for a prompt that is QUEUED (at the
    admission gate, or pre-announced by the router) so by the time it is
    admitted its warm prefix is already resident and it prefills only the
    suffix. Best-effort and side-effect-free on miss: engines without the
    hook (or without a host tier) report False and nothing changes."""
    hook = getattr(self.inference_engine, "prefetch_host_prefix", None)
    if hook is None:
      return False
    try:
      shard = self.get_current_shard(base_shard)
      # Dedupe the router pre-announce against the gate's own on_queued
      # hook: one restore per (shard, prompt) per window is all the host
      # tier can use.
      key = (shard, hash(prompt))
      now = time.monotonic()
      last = self._prefetch_recent.get(key)
      if last is not None and now - last < 30.0:
        return False
      self._prefetch_recent[key] = now
      self._prefetch_recent.move_to_end(key)
      while len(self._prefetch_recent) > 128:
        self._prefetch_recent.popitem(last=False)
      return bool(await hook(shard, prompt))
    except Exception as e:
      if DEBUG >= 1:
        print(f"anticipatory prefix prefetch failed (cold prefill instead): {e!r}")
      return False

  def ingest_peer_metrics(self, node_id: str, summary: dict) -> None:
    self.peer_metrics[node_id] = summary
    self.peer_metrics.move_to_end(node_id)
    self._peer_metrics_at[node_id] = time.monotonic()
    while len(self.peer_metrics) > 64:
      evicted_id, _ = self.peer_metrics.popitem(last=False)
      self._peer_metrics_at.pop(evicted_id, None)

  def peer_metrics_stale(self, node_id: str) -> bool:
    """True when a peer's last summary is older than 3x the topology cadence
    (summaries ride every topology tick, so three missed ticks means a dead
    or wedged peer — its row is history, not signal)."""
    at = self._peer_metrics_at.get(node_id)
    if at is None:
      return True  # pre-stamp row (old peer, direct dict write): treat as stale
    return time.monotonic() - at > 3.0 * max(0.1, self.topology_interval)

  def cluster_metrics_view(self) -> Tuple[Dict[str, dict], Dict[str, dict]]:
    """(nodes, aggregate) for /v1/cluster/metrics: this node's summary plus
    each peer's latest, with stale rows MARKED (`stale: true`) and excluded
    from the ring-wide percentile aggregate — a node that died mid-soak must
    not freeze the cluster's p95 at its last-good histogram forever."""
    nodes: Dict[str, dict] = {self.id: self.metrics_summary()}
    for node_id, summary in self.peer_metrics.items():
      if node_id in nodes:
        continue
      if self.peer_metrics_stale(node_id):
        summary = {**summary, "stale": True}
      nodes[node_id] = summary
    aggregate = aggregate_histograms(
      [s for s in nodes.values() if not s.get("stale")])
    return nodes, aggregate

  async def broadcast_opaque_status(self, request_id: str, status: str) -> None:
    async def send(peer):
      try:
        await asyncio.wait_for(peer.send_opaque_status(request_id, status), timeout=15.0)
      except Exception as e:
        if DEBUG >= 2:
          print(f"broadcast_status to {peer.id()} failed: {e!r}")
    await asyncio.gather(*(send(p) for p in self.peers), return_exceptions=True)
    # Local delivery too (parity: the reference triggers locally as well).
    self.on_opaque_status.trigger_all(request_id, status)

  @property
  def current_topology(self) -> Topology:
    return self.topology
