from xotorch_tpu.utils.helpers import (
  DEBUG,
  DEBUG_DISCOVERY,
  AsyncCallback,
  AsyncCallbackSystem,
  PrefixDict,
  find_available_port,
  get_all_ip_addresses_and_interfaces,
  get_interface_priority_and_type,
  get_or_create_node_id,
  is_port_available,
  pretty_bytes,
  shutdown,
)

__all__ = [
  "DEBUG",
  "DEBUG_DISCOVERY",
  "AsyncCallback",
  "AsyncCallbackSystem",
  "PrefixDict",
  "find_available_port",
  "get_all_ip_addresses_and_interfaces",
  "get_interface_priority_and_type",
  "get_or_create_node_id",
  "is_port_available",
  "pretty_bytes",
  "shutdown",
]
