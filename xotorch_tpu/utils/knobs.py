"""Central registry of every `XOT_*` environment knob.

Single source of truth for the knob surface: name, type, default (in env-var
string form), and one doc line per knob. Three consumers:

- runtime code reads knobs through the typed accessors (`get_int`,
  `get_float`, `get_bool`, `get_str`, `raw`) — a typo'd name raises
  `UnknownKnobError` at the read site instead of silently returning the
  default forever;
- `tools/xotlint` loads this module standalone (it imports only the stdlib,
  never the package) and fails CI on any `XOT_*` env read whose name is not
  registered here;
- the README "Environment knob reference" table is GENERATED from this
  registry (`python -m tools.xotlint --knob-docs`) and drift between the
  two is a lint failure.

Keep `_DEFS` declarative: one `Knob(...)` literal per knob, string-literal
arguments only, so the linter can read it without importing the package.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


class UnknownKnobError(KeyError):
  """An env read referenced an `XOT_*` name that is not registered."""


@dataclass(frozen=True)
class Knob:
  name: str
  kind: str  # "int" | "float" | "bool" | "str" | "json" | "path"
  default: Optional[str]  # env-string form; None = unset (auto/disabled)
  doc: str
  section: str = "General"


# NOTE for editors: keep every field a plain literal (no computed defaults,
# no conditionals) — the registry doubles as documentation and the linter's
# ground truth, so a value a reader can't see at a glance defeats both.
_DEFS: Tuple[Knob, ...] = (
  # ----------------------------------------------------------- engine core
  Knob("XOT_DTYPE", "str", "bfloat16", "Model compute/weight dtype for the JAX engine.", "Engine"),
  Knob("XOT_QUANTIZE", "str", None, "Weight quantization mode (`int8` or `int4`); unset serves full precision.", "Engine"),
  Knob("XOT_KV_QUANT", "str", None, "KV-cache quantization mode (`int8`); unset keeps KV in compute dtype.", "Engine"),
  Knob("XOT_SEED", "int", None, "Sampling PRNG seed; unset derives one from wall-clock time.", "Engine"),
  Knob("XOT_CACHE_LEN", "int", "2048", "Initial per-request KV-cache length (tokens); grows geometrically when exceeded.", "Engine"),
  Knob("XOT_MAX_CACHE_LEN", "int", "32768", "Hard ceiling for per-request KV-cache growth (tokens).", "Engine"),
  Knob("XOT_MAX_RESIDENT_REQUESTS", "int", "8", "Max request states resident per shard context before LRU eviction.", "Engine"),
  Knob("XOT_MAX_RESIDENT_MODELS", "int", "2", "Max model shard contexts resident before LRU eviction of whole models.", "Engine"),
  Knob("XOT_PREFILL_CHUNK", "int", "4096", "Prefill chunk length (tokens): prompts longer than this prefill in chunks.", "Engine"),
  Knob("XOT_COMPILE_CACHE_DIR", "path", None, "Persistent JAX compilation cache directory: a respawned replica's first request loads executables from disk instead of paying the cold-jit stall; unset leaves the JAX default.", "Engine"),
  Knob("XOT_SCAN_PREFILL", "bool", "1", "Use the lax.scan prefill over equal chunks (one compile for any chunk count).", "Engine"),
  Knob("XOT_DECODE_BATCH", "int", "8", "Max concurrent requests fused into one batched decode dispatch.", "Engine"),
  Knob("XOT_BATCH_WINDOW_MS", "float", "0", "Batching window (ms) the decode batcher waits to coalesce submitters; 0 = one event-loop tick.", "Engine"),
  Knob("XOT_DECODE_CHUNK", "int", "8", "Tokens per fused decode dispatch on a single-partition ring; 1 = per-token ring.", "Engine"),
  Knob("XOT_DECODE_CHUNK_MAX", "int", "64", "Adaptive fused-decode chunk ceiling (doubles per dispatch up to this).", "Engine"),
  Knob("XOT_OVERLAP_CHUNKS", "bool", "1", "Overlap fused-decode chunk N+1 dispatch with chunk N host readback.", "Engine"),
  Knob("XOT_OVERLAP_BATCH", "bool", "0", "Overlap batched-decode dispatch with readback (two in-flight batches).", "Engine"),
  # ------------------------------------------------------------- paged KV
  Knob("XOT_PAGED_KV", "bool", "0", "Serve decode from the shared paged KV pool instead of contiguous per-request caches.", "Paged KV"),
  Knob("XOT_KV_PAGE", "int", "128", "Page size (tokens) of the paged KV pool.", "Paged KV"),
  Knob("XOT_KV_POOL_TOKENS", "int", "0", "Total paged-pool capacity in tokens; 0 sizes it automatically.", "Paged KV"),
  Knob("XOT_PAGED_KERNEL", "bool", None, "Force the Pallas ragged paged-attention kernel on/off; unset auto-selects by backend.", "Paged KV"),
  Knob("XOT_PAGED_PREFILL", "bool", "1", "Prefill straight into pool pages under XOT_PAGED_KV (no contiguous commit copy).", "Paged KV"),
  Knob("XOT_RAGGED_PREFILL", "bool", "1", "Kernel-path T>1 segments read pages natively via the ragged kernel (no gathered view); 0 restores the legacy gather+cached-kernel read.", "Paged KV"),
  Knob("XOT_PAGED_SPEC", "bool", "1", "Draft verification runs native to the page arena (ragged query over the request's page table); 0 restores unpage-then-verify.", "Paged KV"),
  Knob("XOT_KV_DEFRAG", "bool", "1", "Background page-pool defragmentation in batcher-idle slots: migrate high pages into low free holes and rewrite only the virtual maps.", "Paged KV"),
  Knob("XOT_KV_DEFRAG_MAX_MOVES", "int", "8", "Max page migrations per idle defrag pass (bounds the donated-copy burst).", "Paged KV"),
  Knob("XOT_PREFILL_COSCHED", "bool", "1", "Co-schedule chunked prefill slices through the decode batcher's drain cycle.", "Paged KV"),
  Knob("XOT_PREFILL_CHUNK_BUDGET", "int", "1", "Prefill segments admitted per decode drain cycle under co-scheduling.", "Paged KV"),
  Knob("XOT_KV_HOST_BYTES", "int", "268435456", "Host-RAM budget (bytes) for the spilled warm-prefix KV tier; 0 disables.", "Paged KV"),
  # --------------------------------------------------------- prefix cache
  Knob("XOT_PREFIX_CACHE", "int", "2", "Prefix-cache entries kept per context (LRU); 0 disables prefix caching.", "Prefix cache"),
  Knob("XOT_PREFIX_CACHE_MIN", "int", "32", "Minimum matched prefix length (tokens) worth reusing from the cache.", "Prefix cache"),
  # ---------------------------------------------------- attention kernels
  Knob("XOT_FLASH_ATTENTION", "bool", None, "Force the Pallas flash-attention prefill kernel on/off; unset auto-selects by backend.", "Kernels"),
  Knob("XOT_FLASH_BLOCK_Q", "int", "128", "Flash-attention query block size.", "Kernels"),
  Knob("XOT_FLASH_BLOCK_K", "int", "128", "Flash-attention key/value block size.", "Kernels"),
  Knob("XOT_FLASH_DECODE", "bool", None, "Force the Pallas flash-decode kernel on/off; unset auto-selects by backend and length.", "Kernels"),
  Knob("XOT_FLASH_DECODE_MIN", "int", "4096", "Minimum KV length (tokens) before flash-decode engages.", "Kernels"),
  Knob("XOT_FD_BLOCK_Q", "int", "128", "Flash-decode query-head block size.", "Kernels"),
  Knob("XOT_FD_BLOCK_K", "int", "256", "Flash-decode key/value block size.", "Kernels"),
  Knob("XOT_INT4_KERNEL", "str", "1", "Fused int4 matmul kernel: `1` on real TPU, `0` off, `force` even off-TPU.", "Kernels"),
  Knob("XOT_INT4_V", "int", "1", "Int4 kernel variant selector (1 or 2).", "Kernels"),
  Knob("XOT_INT8_KERNEL", "str", "0", "Fused int8 matmul kernel: `1` on real TPU, `0` off, `force` even off-TPU.", "Kernels"),
  # ----------------------------------------------------------- speculative
  Knob("XOT_SPECULATE", "int", "0", "Speculative draft depth (tokens per round); 0 disables (8 implied by XOT_DRAFT_MODEL).", "Speculative"),
  Knob("XOT_SPECULATE_WINDOW", "int", "2048", "Backward scan window (tokens) for prompt-lookup draft matching.", "Speculative"),
  Knob("XOT_DRAFT_MODEL", "str", None, "Resident draft model id for model-based speculative decoding.", "Speculative"),
  Knob("XOT_DRAFT_RETRY_S", "float", "300", "Cooldown (s) before retrying a draft model that failed to load.", "Speculative"),
  Knob("XOT_SPEC_EWMA_S", "float", "60", "Time constant (s) of the xot_spec_accept_rate EWMA gauge.", "Speculative"),
  # ------------------------------------------------------------- sharding
  Knob("XOT_TP", "int", None, "Tensor-parallel width of each ring partition's serving mesh (primary knob; overrides XOT_SERVE_TP). 0 forces single-device; unset defers to XOT_SERVE_TP.", "Sharding"),
  Knob("XOT_SERVE_TP", "int", None, "Tensor-parallel degree for serving; unset auto-selects from local devices.", "Sharding"),
  Knob("XOT_SERVE_SP", "int", "0", "Sequence-parallel degree for long-prompt serving prefill.", "Sharding"),
  Knob("XOT_SERVE_EP", "int", "0", "Expert-parallel degree for MoE serving.", "Sharding"),
  Knob("XOT_MAX_SEQ_LEN", "int", None, "Override the model's maximum sequence length (RoPE/table sizing).", "Sharding"),
  # ------------------------------------------------------- training / LoRA
  Knob("XOT_LORA_RANK", "int", "0", "LoRA adapter rank for training; 0 trains/serves without LoRA.", "Training"),
  Knob("XOT_LORA_TARGETS", "str", None, "LoRA target set; `all` extends adapters to MLP slots (default attention-only).", "Training"),
  Knob("XOT_ADAPTERS", "str", None, "Comma-separated `name=path` list of LoRA adapters to serve (multi-LoRA).", "Training"),
  Knob("XOT_LR", "float", "1e-5", "Training learning rate.", "Training"),
  Knob("XOT_SAVE_OPT_STATE", "bool", "1", "Persist/restore optimizer state across training checkpoints.", "Training"),
  # ------------------------------------------------- ring / survivability
  Knob("XOT_HOP_RETRIES", "int", "2", "Retries per ring hop on transient transport failures; 0 = fail-fast.", "Survivability"),
  Knob("XOT_HOP_BACKOFF_S", "float", "0.05", "Base backoff (s) for hop retries (exponential + jitter).", "Survivability"),
  Knob("XOT_REQUEST_DEADLINE_S", "float", "0", "End-to-end request deadline (s); remaining budget rides the hops. 0 disables.", "Survivability"),
  Knob("XOT_STALL_TIMEOUT_S", "float", "30", "Per-node stall watchdog: abort a request with no progress for this long. A mid-dispatch local engine (compiles included) defers the abort, bounded at 4x. 0 disables.", "Survivability"),
  Knob("XOT_HEALTH_INTERVAL_S", "float", "5", "Peer health-check cadence (s); 0 disables the health monitor.", "Survivability"),
  Knob("XOT_HEALTH_FAILS", "int", "2", "Consecutive failed health checks before a peer is evicted.", "Survivability"),
  Knob("XOT_EVICT_COOLDOWN_S", "float", "30", "Seconds an evicted peer stays barred from re-admission by discovery.", "Survivability"),
  Knob("XOT_REQUEST_RESTARTS", "int", "0", "One-shot transparent API restarts after a ring failure (streaming qualifies until its first content chunk).", "Survivability"),
  Knob("XOT_FAULT_SPEC", "json", None, "Test-only: JSON fault-injection rules applied at the peer-handle boundary.", "Survivability"),
  # --------------------------------------------- admission / front door
  Knob("XOT_MAX_INFLIGHT", "int", "0", "Bounded admission: max requests admitted into the ring concurrently by the origin node's API; 0 disables the gate (today's behavior).", "Front door"),
  Knob("XOT_ADMIT_QUEUE_DEPTH", "int", "32", "Bounded admission queue: over-limit requests wait here (FIFO); beyond it they are rejected with HTTP 429 + Retry-After.", "Front door"),
  Knob("XOT_ROUTER_POLL_S", "float", "2", "Router: cadence (s) for polling each replica's /v1/alerts, /v1/queue, and /healthcheck.", "Front door"),
  Knob("XOT_ROUTER_PROBE_TOKENS", "int", "2", "Router: max_tokens of the synthetic canary completion sent to a probing replica.", "Front door"),
  Knob("XOT_ROUTER_PROBES", "int", "2", "Router: consecutive successful canaries required before a drained replica is readmitted.", "Front door"),
  Knob("XOT_ROUTER_MIN_OUT_S", "float", "10", "Router: minimum seconds a drained replica stays out before readmission; doubles (bounded 8x) when the replica flaps.", "Front door"),
  Knob("XOT_ROUTER_FLAP_S", "float", "60", "Router: a re-drain within this many seconds of a readmission counts as flapping (escalates the out-time hysteresis).", "Front door"),
  Knob("XOT_ROUTER_SPILL_DEPTH", "int", "2", "Router: spill a request to the least-loaded healthy replica when its affinity replica's admission queue is at least this deep.", "Front door"),
  Knob("XOT_ROUTER_TIMEOUT_S", "float", "300", "Router: total proxy timeout (s) for one forwarded request.", "Front door"),
  Knob("XOT_ROUTER_DRIFT", "bool", "1", "Router: compare each replica's /v1/history trailing gauges against the fleet median and treat a chronic drifter as a drain-eligible perf_drift suspect.", "Front door"),
  Knob("XOT_ROUTER_DRIFT_POLLS", "int", "3", "Router: consecutive poll ticks a replica must deviate from the fleet median before it is named perf_drift.", "Front door"),
  Knob("XOT_ROUTER_HEDGE_PCT", "float", "0", "Router: request-hedging budget as a percentage of proxied requests (a still-unstarted request is duplicated to the least-loaded other replica, first byte wins); 0 disables hedging.", "Front door"),
  Knob("XOT_ROUTER_HEDGE_FACTOR", "float", "2", "Router: hedge delay as a multiple of the fleet's trailing request p99 (median of routable replicas' /v1/history compacts).", "Front door"),
  Knob("XOT_ROUTER_HEDGE_MIN_S", "float", "0.5", "Router: hedge-delay floor (s); also the delay used while the fleet has no trailing p99 history yet.", "Front door"),
  # ------------------------------------------------------------ elastic fleet
  Knob("XOT_FLEET_MIN", "int", "1", "Fleet controller: minimum replica slots kept spawned (the template's initially-active set).", "Fleet"),
  Knob("XOT_FLEET_MAX", "int", "0", "Fleet controller: maximum concurrently active replica slots; 0 means every slot in the template.", "Fleet"),
  Knob("XOT_FLEET_UP_QUEUE", "int", "1", "Fleet controller: scale up when the fleet-wide admission-queue high-water mark is at least this deep for XOT_FLEET_UP_POLLS consecutive ticks.", "Fleet"),
  Knob("XOT_FLEET_UP_POLLS", "int", "3", "Fleet controller: consecutive controller ticks the queue-depth signal must hold before a scale-up actuates.", "Fleet"),
  Knob("XOT_FLEET_IDLE_POLLS", "int", "60", "Fleet controller: consecutive idle ticks (no queue, no inflight fleet-wide) before a controller-scaled spare replica is retired via the drain path.", "Fleet"),
  Knob("XOT_FLEET_DEAD_POLLS", "int", "3", "Fleet controller: consecutive unreachable-or-scrape-failed polls before an ever-reachable replica is declared dead and respawned.", "Fleet"),
  Knob("XOT_FLEET_COOLDOWN_S", "float", "20", "Fleet controller: minimum seconds between scaling actuations (respawns of dead replicas are exempt).", "Fleet"),
  Knob("XOT_FLEET_BOOT_TIMEOUT_S", "float", "180", "Fleet controller: seconds a freshly spawned replica gets to answer its healthcheck before the spawn counts as a respawn failure.", "Fleet"),
  Knob("XOT_FLEET_LEASE_TTL_S", "float", "15", "Fleet controller: TTL (s) of the actuation lease; a dead lease holder's lease expires and actuation hands over to a surviving router.", "Fleet"),
  Knob("XOT_FLEET_LEASE_PATH", "path", None, "Fleet controller: path of the shared TTL'd lease file gating actuation to one router; unset runs the controller solo (always holds).", "Fleet"),
  Knob("XOT_FLEET_WARM_PREFIXES", "int", "4", "Fleet controller: recent request prefixes pre-announced (/v1/prefetch) at a fresh spawn before it enters rotation (PRESERVE-style warm cold-start).", "Fleet"),
  # ------------------------------------------------------------ KV fabric
  Knob("XOT_FABRIC_PEERS", "str", "", "Fleet-wide KV fabric: comma-separated sibling replica base URLs to probe on a host-tier prefix miss; empty disables static peer probing (router offers still work).", "KV fabric"),
  Knob("XOT_FABRIC_ROLE", "str", "mixed", "Disaggregated serving role: `prefill` (compute KV, offer it, return a handle instead of streaming), `decode` (import offered KV, serve decode), or `mixed` (default: serve everything).", "KV fabric"),
  Knob("XOT_FABRIC_TIMEOUT_S", "float", "2", "KV fabric: per-request transport timeout (s) for peer match probes and entry fetches; a timed-out fetch degrades to a cold prefill.", "KV fabric"),
  Knob("XOT_FABRIC_OFFER_TTL_S", "float", "120", "KV fabric: seconds an announced peer offer stays usable in the local directory before it expires.", "KV fabric"),
  # ------------------------------------------------------------- topology
  Knob("XOT_COORDINATOR", "str", None, "JAX multi-host coordinator address (`host:port`); setting it implies multi-host.", "Topology"),
  Knob("XOT_MULTIHOST", "bool", "0", "Force JAX multi-host initialization.", "Topology"),
  Knob("XOT_NUM_PROCESSES", "int", None, "Process count for JAX multi-host init (required with XOT_COORDINATOR).", "Topology"),
  Knob("XOT_PROCESS_ID", "int", None, "This process's index for JAX multi-host init (required with XOT_COORDINATOR).", "Topology"),
  Knob("XOT_PROBE_TIMEOUT", "float", "120", "Timeout (s) for the device-capability accelerator probe subprocess.", "Topology"),
  Knob("XOT_SKIP_JAX_PROBE", "bool", "0", "Skip the JAX accelerator probe (report CPU-only capabilities).", "Topology"),
  Knob("XOT_PLATFORM", "str", None, "Force the JAX platform (`cpu`/`tpu`/`gpu`) before first device touch.", "Topology"),
  # ------------------------------------------------------ paths / identity
  Knob("XOT_HOME", "path", None, "Root directory for downloads and state; unset uses `~/.xot_tpu`.", "Paths"),
  Knob("XOT_MODEL_DIR", "path", None, "Local directory of model checkpoints (offline serving).", "Paths"),
  Knob("XOT_UUID", "str", None, "Override the persistent per-machine node id.", "Paths"),
  # ------------------------------------------------------- native sidecar
  Knob("XOT_SIDECAR_BIN", "path", None, "Path to a prebuilt native sidecar binary (skips the make step).", "Sidecar"),
  Knob("XOT_SIDECAR_QUANT", "str", None, "Native sidecar weight quantization (`int8`); read by the C++ engine.", "Sidecar"),
  # ------------------------------------------------------------ observability
  Knob("XOT_TRACING", "bool", "1", "Record request/hop spans in the in-process tracer (served at /v1/traces).", "Observability"),
  Knob("XOT_FLIGHT", "bool", "1", "Record runtime events in the per-node flight recorder (served at /v1/debug/flight).", "Observability"),
  Knob("XOT_FLIGHT_EVENTS", "int", "4096", "Flight-recorder ring capacity (events).", "Observability"),
  Knob("XOT_FLIGHT_SNAPSHOTS", "int", "16", "Frozen flight-recorder snapshots kept per node (LRU).", "Observability"),
  Knob("XOT_FLIGHT_DUMP_DIR", "path", None, "Post-mortem spool: on SIGTERM/SIGINT the node dumps its flight ring + frozen snapshots here as JSON; unset disables.", "Observability"),
  Knob("XOT_ANATOMY", "bool", "1", "Critical-path latency anatomy: hop clock stamps, skew-corrected per-request stage breakdowns (served at /v1/anatomy). 0 removes the clock field from the wire entirely.", "Observability"),
  Knob("XOT_ANATOMY_RESERVOIR", "int", "256", "Recent stage breakdowns kept per node for /v1/anatomy percentiles and diffs.", "Observability"),
  Knob("XOT_ANATOMY_CLOCK_WINDOW", "int", "64", "Per-peer window of one-way clock-delta samples the skew estimator min-filters.", "Observability"),
  Knob("XOT_ANATOMY_DELAY_S", "float", "0.35", "Seconds after a request finishes before the origin assembles its breakdown (lets remote span shards arrive over the status bus).", "Observability"),
  Knob("XOT_ANATOMY_SKEW_NS", "int", "0", "Test-only: artificial offset (ns) added to this node's anatomy wall clock — the skew-injection point for offset-recovery proofs.", "Observability"),
  Knob("XOT_PERF_ATTR", "bool", "1", "Live roofline attribution: per-dispatch time/bytes/FLOPs accounting served at /v1/perf.", "Observability"),
  Knob("XOT_PERF_EWMA_S", "float", "30", "Time constant (s) of the EWMA throughput/utilization gauges (xot_decode_tok_s and friends).", "Observability"),
  Knob("XOT_DEVICE_TRACE_MAX_S", "float", "120", "Auto-stop a /v1/trace/device/start jax.profiler session after this many seconds; 0 disables the cap.", "Observability"),
  # ------------------------------------------------------ alerting / SLOs
  Knob("XOT_ALERT", "bool", "1", "Evaluate SLO burn-rate alert rules on a background cadence (served at /v1/alerts).", "Alerting"),
  Knob("XOT_ALERT_EVAL_S", "float", "5", "Alert-rule evaluation cadence (seconds).", "Alerting"),
  Knob("XOT_ALERT_FAST_S", "float", "120", "Fast burn-rate window (seconds) of the multi-window SLO rules.", "Alerting"),
  Knob("XOT_ALERT_SLOW_S", "float", "600", "Slow burn-rate window (seconds) of the multi-window SLO rules.", "Alerting"),
  Knob("XOT_ALERT_BURN_FAST", "float", "14.4", "Fast-window burn-rate threshold (error-budget multiples) a rule must exceed to fire.", "Alerting"),
  Knob("XOT_ALERT_BURN_SLOW", "float", "6", "Slow-window burn-rate threshold (error-budget multiples) a rule must exceed to fire.", "Alerting"),
  Knob("XOT_ALERT_PENDING_S", "float", "10", "Seconds the burn condition must hold before a pending alert transitions to firing.", "Alerting"),
  Knob("XOT_ALERT_RESOLVE_S", "float", "60", "Hysteresis: seconds the burn condition must stay clear before a firing alert resolves.", "Alerting"),
  Knob("XOT_ALERT_SNAPSHOTS", "int", "256", "Bounded ring of timestamped metric snapshots the burn windows are computed over.", "Alerting"),
  Knob("XOT_ALERT_HISTORY", "int", "64", "Recent resolved alerts kept for /v1/alerts (bounded).", "Alerting"),
  Knob("XOT_ALERT_DEVICE_TRACE", "bool", "0", "Capture-on-anomaly: a firing alert starts the bounded device trace (auto-stops after XOT_DEVICE_TRACE_MAX_S).", "Alerting"),
  Knob("XOT_ALERT_RTT_TAU_S", "float", "30", "Time constant (s) of the per-peer hop send RTT EWMAs (xot_peer_hop_seconds).", "Alerting"),
  Knob("XOT_ALERT_HOP_DEGRADED_S", "float", "0.2", "Absolute hop-RTT floor (s) below which a peer is never scored degraded.", "Alerting"),
  Knob("XOT_ALERT_DEGRADED_FACTOR", "float", "3", "A peer whose hop RTT or per-dispatch compute exceeds this multiple of the ring median is scored degraded.", "Alerting"),
  Knob("XOT_SLO_TTFT_S", "float", "10", "TTFT SLO target (s) the XOT_SLO_TARGET fraction of requests must beat.", "Alerting"),
  Knob("XOT_SLO_E2E_S", "float", "60", "End-to-end request latency SLO target (s).", "Alerting"),
  Knob("XOT_SLO_TARGET", "float", "0.99", "Fraction of requests that must meet each latency SLO target (error budget = 1 - target; must leave budget * XOT_ALERT_BURN_FAST below 1 or the rule can never fire).", "Alerting"),
  Knob("XOT_SLO_ERROR_RATE", "float", "0.01", "Failed-request budget: the fraction of requests that may abort before the error-rate rule burns.", "Alerting"),
  # --------------------------------------------------- metrics history / drift
  Knob("XOT_HISTORY", "bool", "1", "Metrics history: sample windowed deltas of the node's own gauges on a background cadence (served at /v1/history); 0 disables the sampler entirely — no task, no wire keys, byte-identical serving.", "History"),
  Knob("XOT_HISTORY_SAMPLE_S", "float", "10", "History sampling cadence (seconds): one windowed-delta gauge sample per tick.", "History"),
  Knob("XOT_HISTORY_SAMPLES", "int", "360", "Fine-tier samples kept before the oldest are merged into the next-coarser tier (at the default 10 s cadence: one hour at full resolution).", "History"),
  Knob("XOT_HISTORY_MERGE", "int", "8", "Samples merged into one duration-weighted bucket when a history tier overflows into the next-coarser tier.", "History"),
  Knob("XOT_HISTORY_COARSE", "int", "336", "Buckets kept in each of the two coarser history tiers (mid keeps merge-fold buckets, old keeps merge^2-fold).", "History"),
  Knob("XOT_HISTORY_DIR", "path", None, "JSONL spool directory for history samples: restarts and soak teardowns keep the record (restored rows are marked as a restart boundary); unset keeps history in memory only.", "History"),
  Knob("XOT_DRIFT", "bool", "1", "Evaluate chronic perf-drift rules over the metrics history inside the alert loop (requires XOT_HISTORY and XOT_ALERT); fires the perf_drift alert class.", "History"),
  Knob("XOT_DRIFT_WINDOW_S", "float", "120", "Recent window (s) a drift rule averages over — also the trailing-mean window of the history compact the router and ring peers compare.", "History"),
  Knob("XOT_DRIFT_BASELINE_S", "float", "600", "Trailing baseline window (s) a drift rule compares its recent window against; the baseline ends where the recent window begins.", "History"),
  Knob("XOT_DRIFT_RATIO", "float", "0.25", "Relative worsening vs the gauge's own trailing baseline before a drift rule's condition holds (direction-aware: tok/s down, rtt up).", "History"),
  Knob("XOT_DRIFT_PEER_RATIO", "float", "0.5", "Relative worsening vs the median of peer nodes' trailing gauges before a drift rule's condition holds.", "History"),
  Knob("XOT_DRIFT_MIN_SAMPLES", "int", "3", "Minimum samples carrying the gauge in each compared window before a drift rule may evaluate (thin evidence never pages).", "History"),
  Knob("XOT_DRIFT_PENDING_S", "float", "30", "Seconds a drift condition must hold before the pending perf_drift alert transitions to firing.", "History"),
  Knob("XOT_DRIFT_RESOLVE_S", "float", "60", "Hysteresis: seconds a drift condition must stay clear before a firing perf_drift alert resolves.", "History"),
  # ------------------------------------------------------- soak / load gen
  Knob("XOT_SOAK_SECONDS", "float", "60", "Soak load duration (s) for `python -m tools.soak` when --seconds is not given.", "Soak"),
  Knob("XOT_SOAK_RPS", "float", "1.5", "Mean open-loop arrival rate (requests/s) for the soak load generator.", "Soak"),
  Knob("XOT_SOAK_PROCS", "int", "2", "Ring size (node processes) the soak orchestrator spawns.", "Soak"),
  Knob("XOT_SOAK_STREAM_FRACTION", "float", "0.5", "Fraction of soak requests issued as SSE streaming completions.", "Soak"),
  Knob("XOT_SOAK_SESSION_REUSE", "float", "0.3", "Probability a soak request reuses a session prefix (prefix-cache exercise).", "Soak"),
  Knob("XOT_SOAK_RECON_TOL_S", "float", "2.5", "Absolute slack (s) allowed between client- and server-observed latency percentiles in the soak verdict.", "Soak"),
  Knob("XOT_SOAK_SEED", "int", "1234", "PRNG seed for the soak load generator (arrivals, lengths, mixes).", "Soak"),
)

REGISTRY: Dict[str, Knob] = {k.name: k for k in _DEFS}

_UNSET = object()
_FALSE_STRINGS = frozenset(("", "0", "false", "no", "off"))


def _lookup(name: str) -> Knob:
  try:
    return REGISTRY[name]
  except KeyError:
    raise UnknownKnobError(
      f"{name} is not a registered knob — add it to xotorch_tpu/utils/knobs.py"
    ) from None


def raw(name: str, default=_UNSET) -> Optional[str]:
  """The env value as a string, or the registered default (which may be
  None = unset) — the exact-substitute for `os.getenv` that still fails
  loudly on typo'd knob names. A set-but-EMPTY value is returned verbatim:
  tri-state call sites distinguish `XOT_X=` (set: forces the non-"1"
  branch, e.g. kernel off) from `XOT_X` absent (auto-select); the numeric
  accessors below map empty to the default instead (the historical
  `... or 0` idiom)."""
  knob = _lookup(name)
  value = os.environ.get(name)
  if value is None:
    return knob.default if default is _UNSET else default
  return value


def get_str(name: str, default=_UNSET) -> Optional[str]:
  return raw(name, default)


def _required(name: str):
  raise RuntimeError(f"knob {name} has no default and is not set in the environment")


def _numeric(name: str, default, cast):
  value = raw(name, default)
  if isinstance(value, str) and value.strip() == "":
    # Empty value == unset for numbers (`XOT_X= prog` must not crash).
    knob = _lookup(name)
    value = knob.default if default is _UNSET else default
  if value is None:
    return None if default is not _UNSET else _required(name)
  return cast(value)


def get_int(name: str, default=_UNSET) -> Optional[int]:
  return _numeric(name, default, int)


def get_float(name: str, default=_UNSET) -> Optional[float]:
  return _numeric(name, default, float)


def get_bool(name: str, default=_UNSET) -> Optional[bool]:
  """Truthiness matching the historical call sites: "0"/"false"/"no"/"off"
  (any case) and set-but-empty are False, any other set value is True."""
  value = raw(name, default)
  if value is None:
    return None if default is not _UNSET else _required(name)
  if isinstance(value, bool):
    return value
  return str(value).strip().lower() not in _FALSE_STRINGS


def knob_table_markdown() -> str:
  """The README "Environment knob reference" section body — generated so
  docs can never drift from the registry (xotlint's doc-drift checker
  compares this rendering against the committed README)."""
  lines = []
  section = None
  for knob in _DEFS:
    if knob.section != section:
      section = knob.section
      lines.append(f"\n**{section}**\n")
      lines.append("| Knob | Type | Default | Description |")
      lines.append("| --- | --- | --- | --- |")
    default = "_unset_" if knob.default is None else f"`{knob.default}`"
    lines.append(f"| `{knob.name}` | {knob.kind} | {default} | {knob.doc} |")
  return "\n".join(lines).strip() + "\n"
