"""Foundation utilities: debug flags, async pub/sub, ports, node identity, NICs.

Capability parity with the reference foundation layer
(/root/reference/xotorch/helpers.py:19-389) re-implemented for this runtime:
psutil-based NIC enumeration (the reference shells out to scapy/system_profiler),
asyncio-native callback conditions, and tmp-dir persisted node identity.
"""
from __future__ import annotations

import asyncio
import os
import random
import socket
import sys
import tempfile
import uuid
from typing import Awaitable, Callable, Dict, Generic, List, Optional, Tuple, TypeVar

from xotorch_tpu.utils import knobs

DEBUG = int(os.getenv("DEBUG", "0"))
DEBUG_DISCOVERY = int(os.getenv("DEBUG_DISCOVERY", "0"))

T = TypeVar("T")
K = TypeVar("K")


class AsyncCallback(Generic[T]):
  """A single awaitable event stream: observers plus a predicate-gated wait.

  Parity: AsyncCallback (/root/reference/xotorch/helpers.py:104-133).
  """

  def __init__(self) -> None:
    self.condition: asyncio.Condition = asyncio.Condition()
    self.result: Optional[Tuple[T, ...]] = None
    self.observers: List[Callable[..., None]] = []

  async def wait(self, check_condition: Callable[..., bool], timeout: Optional[float] = None) -> Tuple[T, ...]:
    async with self.condition:
      await asyncio.wait_for(
        self.condition.wait_for(lambda: self.result is not None and check_condition(*self.result)),
        timeout,
      )
      assert self.result is not None
      return self.result

  def on_next(self, callback: Callable[..., None]) -> None:
    self.observers.append(callback)

  def set(self, *args: T) -> None:
    self.result = args
    for observer in self.observers:
      observer(*args)
    spawn_detached(self._notify())

  async def _notify(self) -> None:
    async with self.condition:
      self.condition.notify_all()


class AsyncCallbackSystem(Generic[K, T]):
  """Named registry of AsyncCallbacks with broadcast trigger.

  Parity: AsyncCallbackSystem (/root/reference/xotorch/helpers.py:136-149).
  """

  def __init__(self) -> None:
    self.callbacks: Dict[K, AsyncCallback[T]] = {}

  def register(self, name: K) -> AsyncCallback[T]:
    if name not in self.callbacks:
      self.callbacks[name] = AsyncCallback[T]()
    return self.callbacks[name]

  def deregister(self, name: K) -> None:
    self.callbacks.pop(name, None)

  def trigger(self, name: K, *args: T) -> None:
    if name in self.callbacks:
      self.callbacks[name].set(*args)

  def trigger_all(self, *args: T) -> None:
    for callback in list(self.callbacks.values()):
      callback.set(*args)


class PrefixDict(Generic[K, T]):
  """Dict queryable by key prefix (parity: helpers.py:329-343)."""

  def __init__(self) -> None:
    self._data: Dict[str, T] = {}

  def add(self, key: str, value: T) -> None:
    self._data[key] = value

  def find_prefix(self, argument: str) -> List[Tuple[str, T]]:
    return [(key, value) for key, value in self._data.items() if argument.startswith(key)]

  def find_longest_prefix(self, argument: str) -> Optional[Tuple[str, T]]:
    matches = self.find_prefix(argument)
    if not matches:
      return None
    return max(matches, key=lambda x: len(x[0]))


_DETACHED_TASKS: set = set()


def _report_task_exception(task: "asyncio.Task") -> None:
  """Done-callback: a detached task that died of an exception is logged
  deterministically at the next loop tick — not maybe-later at GC time via
  asyncio's "Task exception was never retrieved" handler (which fires only
  if the loop is still running when the ref drops). Some spawn sites DO
  await the task (download dedup, the API token pumps) and handle its
  exception themselves; deferring one tick lets their wakeup retrieve it
  first (retrieval clears the task's traceback-log flag), so only truly
  unobserved failures are reported."""
  if task.cancelled():
    return

  def _check() -> None:
    if getattr(task, "_log_traceback", True) is False:
      return  # an awaiter retrieved the exception and owns handling it
    exc = task.exception()
    if exc is not None:
      print(f"detached task {task.get_name()} failed: {exc!r}", file=sys.stderr)

  try:
    asyncio.get_running_loop().call_soon(_check)
  except RuntimeError:  # loop already closed: report synchronously
    _check()


def spawn_detached(coro, registry: Optional[set] = None) -> "asyncio.Task":
  """create_task with a STRONG reference (asyncio keeps only weak refs to
  tasks — an untracked fire-and-forget task can be garbage-collected
  mid-flight, silently dropping the work) and deterministic exception
  logging. One helper so every fire-and-forget site shares the same idiom
  (xotlint's async-safety checker bans raw create_task outside this
  module); pass `registry` to scope the refs to an owner (e.g. a server's
  in-flight hops), else a module-global set holds them until done."""
  reg = registry if registry is not None else _DETACHED_TASKS
  task = asyncio.create_task(coro)
  reg.add(task)
  task.add_done_callback(reg.discard)
  task.add_done_callback(_report_task_exception)
  return task


def is_port_available(port: int, host: str = "") -> bool:
  with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
      s.bind((host, port))
      return True
    except OSError:
      return False


def _used_ports_file() -> str:
  return os.path.join(tempfile.gettempdir(), "xot_tpu_used_ports")


def find_available_port(host: str = "", min_port: int = 49152, max_port: int = 65535) -> int:
  """Random free port, avoiding ports this host's processes recently claimed.

  The cross-process used-ports file mirrors the reference behavior
  (/root/reference/xotorch/helpers.py:47-76) so several peers starting at
  once on one machine don't race for the same port.
  """
  used: List[int] = []
  try:
    with open(_used_ports_file(), "r") as f:
      used = [int(line) for line in f.read().split() if line.strip().isdigit()]
  except OSError:
    pass
  used = used[-100:]
  for _ in range(200):
    port = random.randint(min_port, max_port)
    if port not in used and is_port_available(port, host):
      try:
        with open(_used_ports_file(), "w") as f:
          f.write("\n".join(str(p) for p in used + [port]))
      except OSError:
        pass
      return port
  raise RuntimeError("No available ports in range")


def get_or_create_node_id() -> str:
  """Persistent per-machine node UUID (parity: helpers.py:182-205)."""
  override = knobs.get_str("XOT_UUID", None)
  if override:
    return override
  id_file = os.path.join(tempfile.gettempdir(), ".xot_tpu_node_id")
  try:
    if os.path.isfile(id_file):
      with open(id_file, "r") as f:
        stored = f.read().strip()
      if stored:
        return stored
    node_id = str(uuid.uuid4())
    with open(id_file, "w") as f:
      f.write(node_id)
    return node_id
  except OSError:
    return str(uuid.uuid4())


def get_all_ip_addresses_and_interfaces() -> List[Tuple[str, str]]:
  """All (ipv4, interface) pairs on this host, loopback last.

  psutil-based (the reference used scapy, helpers.py:234-248); falls back to
  a loopback entry so single-machine dev always works.
  """
  try:
    import psutil
    pairs: List[Tuple[str, str]] = []
    for ifname, addrs in psutil.net_if_addrs().items():
      for addr in addrs:
        if addr.family == socket.AF_INET and addr.address:
          pairs.append((addr.address, ifname))
    pairs.sort(key=lambda p: p[0].startswith("127."))
    if pairs:
      return pairs
  except Exception as e:
    # No psutil / permission-denied NIC enumeration: single-machine dev
    # still works off loopback, but say so — a silent fallback here makes
    # "discovery finds nobody" undiagnosable on multi-NIC hosts.
    if DEBUG >= 1:
      print(f"NIC enumeration failed ({e!r}); falling back to loopback only")
  return [("127.0.0.1", "lo")]


def get_interface_priority_and_type(ifname: str) -> Tuple[int, str]:
  """Rank an interface for peer-address conflict resolution.

  Same ordering intent as the reference (helpers.py:280-315): container >
  loopback > point-to-point fabric > ethernet > wifi > other > vpn.
  """
  name = ifname.lower()
  if name.startswith(("docker", "br-", "veth", "cni", "flannel", "calico")):
    return (7, "Container Virtual")
  if name.startswith("lo"):
    return (6, "Loopback")
  if name.startswith(("ib", "bond", "thunderbolt")):
    return (5, "Fabric")
  if name.startswith(("eth", "en", "eno", "ens", "enp")):
    return (4, "Ethernet")
  if name.startswith(("wl", "wifi", "wlan")):
    return (3, "WiFi")
  if name.startswith(("tun", "tap", "vpn", "wg", "utun", "zt", "ts")):
    return (1, "VPN")
  return (2, "Other")


def pretty_bytes(size_in_bytes: float) -> str:
  for unit, divisor in (("TB", 1 << 40), ("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10)):
    if size_in_bytes >= divisor:
      return f"{size_in_bytes / divisor:.2f} {unit}"
  return f"{int(size_in_bytes)} B"


async def shutdown(signal_or_none, loop: asyncio.AbstractEventLoop, server) -> None:
  """Cancel outstanding tasks and stop the node (parity: helpers.py:318-326)."""
  if DEBUG >= 1:
    print(f"Received exit signal {signal_or_none}; shutting down")
  tasks = [t for t in asyncio.all_tasks(loop) if t is not asyncio.current_task()]
  for task in tasks:
    task.cancel()
  await asyncio.gather(*tasks, return_exceptions=True)
  if server is not None:
    stop = getattr(server, "stop", None)
    if stop is not None:
      result = stop()
      if isinstance(result, Awaitable):
        await result
  loop.stop()
