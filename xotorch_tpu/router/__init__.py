"""SLO-driven front door: N independent ring replicas behind one endpoint.

One ring is one failure domain and one throughput ceiling. This package
runs several INDEPENDENT rings ("replicas", each its own discovery domain
and OpenAI API) behind a single OpenAI-compatible endpoint, and is the
component that finally ACTS on eight PRs of advisory observability:

- **Placement** (`route`): requests hash by session/prefix key
  (`prefix_key`, rendezvous hashing) to the replica whose HBM or host tier
  already holds their prefix — the PR 3 warm path — with queue-depth-aware
  spill to the least-loaded replica when the affinity target's admission
  queue (the `/v1/queue` surface) is backed up. The router also
  pre-announces a queued request's prompt to the target (`/v1/prefetch`)
  so the host-to-HBM restore runs while the request is still in flight
  (PRESERVE, arXiv 2501.08192).
- **Lifecycle** (`ReplicaLifecycle`): a firing burn-rate alert or a named
  gray-failure `suspect` (the PR 9 localization, advisory until now) moves
  a replica healthy -> draining -> probing -> readmitted. Draining stops
  new admissions but lets inflight streams finish; probing sends synthetic
  canary completions; readmission takes `XOT_ROUTER_PROBES` consecutive
  successes plus a minimum out-time that DOUBLES when the replica flaps
  (re-drained soon after readmission), so an oscillating replica spends
  exponentially longer out instead of thrashing the fleet.

This module is the PURE half — state machine, hashing, placement — fully
unit-testable with injected clocks and no processes; `router/app.py` is
the asyncio process that drives it against real replicas. Cross-replica
weight handling (shared host-RAM weight cache, staggered rollout) follows
the replica-sharding analysis of arXiv 2004.13336: replicas share nothing
at runtime, so one replica's failure domain never reaches another.
"""
from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from xotorch_tpu.orchestration.history import DRIFT_RULES, median, worse_by
from xotorch_tpu.utils import knobs

# Escalation cap for the flap hysteresis: a replica that keeps flapping
# waits at most 8x the base out-time between readmissions.
MAX_OUT_MULTIPLIER = 8


def fleet_trailing_medians(compacts: Iterable[dict],
                           min_n: int = 1) -> Dict[str, float]:
  """Per-metric median of the fleet's trailing history gauges. `compacts`
  are /v1/history compact dicts from the replicas a drifting one should be
  judged against (healthy + reachable only — a drained replica's polluted
  gauges must not drag the fleet's definition of normal). A peer's value
  joins the median only when it rests on at least `min_n` samples: one
  cold-start observation is not a reference."""
  by_metric: Dict[str, List[float]] = {}
  for c in compacts:
    trailing = (c or {}).get("trailing")
    if not isinstance(trailing, dict):
      continue
    counts = (c or {}).get("trailing_n")
    for metric, v in trailing.items():
      # A compact without counts (older peer) reports unknown depth = 1.
      n = int(counts.get(metric) or 0) if isinstance(counts, dict) else 1
      if n >= min_n:
        by_metric.setdefault(metric, []).append(float(v))
  out = {}
  for metric, vals in by_metric.items():
    m = median(vals)
    if m is not None:
      out[metric] = m
  return out


def name_drift(own: Optional[dict], peer_medians: Dict[str, float],
               ratio: float, min_n: int = 1) -> Optional[dict]:
  """The differential-drift verdict for one replica: its worst watched
  DIFFERENTIAL gauge deviating (direction-aware, past the rule's absolute
  floor) from the PEER median by at least `ratio`, or None when it tracks
  the fleet. Volume-coupled gauges (tok/s, jit-miss, fetch bytes) are
  excluded: they diverge whenever load is uneven — which the router's own
  drains and spills cause — so comparing them across replicas is a
  feedback loop, and a deviation resting on fewer than `min_n` samples
  (a cold-start compile's lone TTFT) is not chronic evidence. Pure — the
  router's poll loop feeds it compacts and debounces the result over
  consecutive polls."""
  trailing = (own or {}).get("trailing")
  if not isinstance(trailing, dict):
    return None
  counts = (own or {}).get("trailing_n")
  worst = None
  for rule in DRIFT_RULES:
    if not rule.differential:
      continue
    v = trailing.get(rule.metric)
    ref = peer_medians.get(rule.metric)
    # A compact without counts (older peer) reports unknown depth = 1.
    n = int(counts.get(rule.metric) or 0) if isinstance(counts, dict) else 1
    if v is None or ref is None or n < min_n:
      continue
    dev = worse_by(float(v), float(ref), rule.worse)
    if dev < ratio or abs(float(v) - float(ref)) < rule.floor:
      continue
    if worst is None or dev > worst["worse_by"]:
      worst = {"metric": rule.metric, "value": round(float(v), 6),
               "peer_median": round(float(ref), 6), "worse_by": round(dev, 4)}
  return worst


def hedge_delay_s(compacts: Iterable[dict], factor: float, min_s: float) -> float:
  """The hedge trigger delay: `factor` x the fleet's trailing p99 request
  latency (median across the routable replicas' /v1/history compacts — a
  single slow replica must not inflate the delay that exists to route
  around it), floored at `min_s`. Falls back to the p50 when no replica
  has served enough traffic for a p99, and to the bare floor on a cold
  fleet — hedging never waits on data that does not exist."""
  p99s, p50s = [], []
  for c in compacts:
    trailing = (c or {}).get("trailing")
    if not isinstance(trailing, dict):
      continue
    if trailing.get("request_p99_s") is not None:
      p99s.append(float(trailing["request_p99_s"]))
    if trailing.get("request_p50_s") is not None:
      p50s.append(float(trailing["request_p50_s"]))
  m = median(p99s)
  if m is None:
    m = median(p50s)
  return max(min_s, factor * m) if m is not None else max(0.0, min_s)


def prefix_key(body: dict) -> str:
  """Stable session/prefix affinity key for an OpenAI chat body: the first
  user message's leading characters — exactly the shared session head a
  returning chat user re-sends verbatim (and the shape tools/soak/loadgen
  reuses), so session traffic rendezvous-hashes to the replica whose HBM
  or host tier already holds the prefix. An explicit `user` field (the
  OpenAI end-user id) wins when present: it is the stronger session
  signal and survives prompt edits."""
  user = body.get("user")
  if isinstance(user, str) and user:
    return f"user:{user}"
  for m in body.get("messages") or []:
    if not isinstance(m, dict) or m.get("role") != "user":
      continue
    content = m.get("content")
    if isinstance(content, list):  # multi-part: concatenate the text parts
      content = " ".join(p.get("text", "") for p in content
                         if isinstance(p, dict) and p.get("type") == "text")
    return str(content or "")[:160]
  return ""


def rendezvous(key: str, names: Sequence[str]) -> Optional[str]:
  """Highest-random-weight (rendezvous) choice: every router instance maps
  the same key to the same replica with no shared state, and removing a
  replica only remaps the keys that lived on it."""
  best, best_score = None, None
  for name in names:
    score = hashlib.sha1(f"{key}|{name}".encode()).digest()
    if best_score is None or score > best_score:
      best, best_score = name, score
  return best


def least_loaded(views: List[dict]) -> Optional[dict]:
  """The lightest replica view by (admission queue depth, estimated wait,
  name) — ONE definition of "least loaded", shared by route()'s spill and
  the router's 429 retry so placement and retry can never disagree."""
  if not views:
    return None
  return min(views, key=lambda v: (int(v.get("queued") or 0),
                                   float(v.get("est_wait_s") or 0.0),
                                   str(v["name"])))


def route(key: str, views: List[dict], spill_depth: int) -> Optional[Tuple[str, bool]]:
  """Pick a replica for one request. `views` are the ROUTABLE replicas'
  load compacts: {name, queued, est_wait_s} (from each replica's
  /v1/queue poll). Affinity first — rendezvous on the prefix key — then
  queue-depth-aware spill: when the affinity target's admission queue is
  at least `spill_depth` deep and another replica is strictly less
  loaded, the request goes to the least-loaded one instead (warm prefix
  lost, but a queue wait is lost time for certain). Returns
  (replica_name, spilled) or None when nothing is routable."""
  if not views:
    return None
  by_name = {str(v["name"]): v for v in views}
  pick = rendezvous(key, sorted(by_name))
  if spill_depth > 0:
    target_q = int(by_name[pick].get("queued") or 0)
    if target_q >= spill_depth:
      least = least_loaded(views)
      if str(least["name"]) != pick and int(least.get("queued") or 0) < target_q:
        return str(least["name"]), True
  return pick, False


class ReplicaLifecycle:
  """healthy -> draining -> probing -> readmitted (healthy), per replica.

  Pure and clock-injected: `note_status` consumes one poll observation
  (firing alert count, named suspect, inflight requests, reachability) and
  `note_probe` one canary outcome; both return a transition dict (what the
  router records as a flight event) or None. Only `healthy` replicas are
  routable — draining/probing replicas accept no new traffic, which is
  what lets their inflight streams finish undisturbed."""

  def __init__(self, name: str, probes_required: Optional[int] = None,
               min_out_s: Optional[float] = None,
               flap_window_s: Optional[float] = None):
    self.name = name
    self.probes_required = (probes_required if probes_required is not None
                            else max(1, knobs.get_int("XOT_ROUTER_PROBES")))
    self.min_out_s = (min_out_s if min_out_s is not None
                      else max(0.0, knobs.get_float("XOT_ROUTER_MIN_OUT_S")))
    self.flap_window_s = (flap_window_s if flap_window_s is not None
                          else max(0.0, knobs.get_float("XOT_ROUTER_FLAP_S")))
    self.state = "healthy"
    self.drained_at: Optional[float] = None
    self.drain_reason: Optional[str] = None
    self.readmitted_at: Optional[float] = None
    self.out_multiplier = 1
    self.probe_successes = 0
    self.drains_total = 0
    self.readmits_total = 0
    self.probe_failures_total = 0
    # A replica that has NEVER answered a poll is JOINING (booting, port
    # not bound yet), not failing: unreachability only drains once the
    # replica has been seen alive — otherwise every boot would burn a
    # drain/probe/readmit cycle and pollute the lifecycle counters.
    self.ever_reachable = False

  @property
  def routable(self) -> bool:
    return self.state == "healthy"

  def required_out_s(self) -> float:
    """Current minimum out-time: the flap-escalated hysteresis floor."""
    return self.min_out_s * self.out_multiplier

  def _transition(self, to: str, now: float, reason: str = "") -> dict:
    self.state = to
    return {"replica": self.name, "transition": to, "at": now, "reason": reason}

  def note_status(self, now: float, firing: int = 0, suspect: Optional[str] = None,
                  inflight: int = 0, reachable: bool = True) -> Optional[dict]:
    """One poll observation. Transitions:
    - healthy -> draining on a firing alert, a named suspect, or an
      unreachable replica (flap escalation applies when the drain lands
      inside the flap window of the last readmission);
    - draining -> probing once the replica is reachable, its inflight
      count has drained to zero, and the ACCUSATION has cleared — the
      firing alert resolved AND no suspect (gray localization or
      perf_drift) is still named. Probing while the cause persists sends
      canaries INTO the fault: they pollute the replica's latency
      histograms with traffic no client sees and can readmit a replica
      whose rot merely paused;
    - probing -> draining when the burn re-fires mid-probe.
    A never-yet-reachable replica (still booting) takes no transition:
    it is not routable anyway, and draining it would burn a
    probe/readmit cycle on every boot."""
    if reachable:
      self.ever_reachable = True
    elif not self.ever_reachable:
      return None
    bad = bool(firing) or bool(suspect) or not reachable
    if self.state == "healthy":
      if not bad:
        return None
      if (self.readmitted_at is not None and self.flap_window_s > 0
          and now - self.readmitted_at < self.flap_window_s):
        self.out_multiplier = min(MAX_OUT_MULTIPLIER, self.out_multiplier * 2)
      else:
        self.out_multiplier = 1
      self.drained_at = now
      self.probe_successes = 0
      self.drains_total += 1
      why = ("unreachable" if not reachable
             else f"suspect:{suspect}" if suspect else f"alerts_firing:{firing}")
      self.drain_reason = why
      return self._transition("draining", now, why)
    if self.state == "draining":
      if reachable and inflight <= 0 and not firing and not suspect:
        return self._transition("probing", now, "drained")
      return None
    if self.state == "probing" and bad:
      # The accusation came back mid-probe (burn re-fired, suspect
      # re-named, or the replica vanished): a full re-drain, not a pause —
      # the minimum out-time restarts from NOW (otherwise the original
      # drain's clock would let a replica whose alert merely dips readmit
      # seconds after each dip, the oscillation the hysteresis exists to
      # prevent). Without the suspect arm, note_probe could readmit a
      # still-accused replica and the next poll would instantly re-drain
      # it with flap escalation.
      self.probe_successes = 0
      self.drained_at = now
      self.drains_total += 1
      why = ("alert re-fired" if firing
             else f"suspect:{suspect}" if suspect else "unreachable")
      self.drain_reason = why
      return self._transition("draining", now, why)
    return None

  def note_probe(self, ok: bool, now: float) -> Optional[dict]:
    """One synthetic canary outcome (probing state only). Readmission takes
    `probes_required` CONSECUTIVE successes and at least the (flap-
    escalated) minimum out-time since the drain; any failure resets the
    streak — a replica that can't serve a 2-token canary stays out."""
    if self.state != "probing":
      return None
    if not ok:
      self.probe_failures_total += 1
      self.probe_successes = 0
      return None
    self.probe_successes += 1
    out_for = now - (self.drained_at if self.drained_at is not None else now)
    if self.probe_successes >= self.probes_required and out_for >= self.required_out_s():
      self.readmitted_at = now
      self.readmits_total += 1
      self.drain_reason = None
      return self._transition("healthy", now, "readmitted")
    return None

  def snapshot(self) -> dict:
    """JSON row for /v1/router and the soak's router scrape."""
    return {
      "name": self.name, "state": self.state,
      "drain_reason": self.drain_reason,
      "drained_at": self.drained_at, "readmitted_at": self.readmitted_at,
      "out_multiplier": self.out_multiplier,
      "probe_successes": self.probe_successes,
      "drains_total": self.drains_total, "readmits_total": self.readmits_total,
      "probe_failures_total": self.probe_failures_total,
    }


def replica_names(urls: Iterable[str]) -> Dict[str, str]:
  """Stable short names for replica base URLs: r0, r1, ... in the order
  given (the CLI's --replica order), so logs, /v1/router rows, and soak
  scrapes agree on identity without parsing URLs."""
  return {f"r{i}": url.rstrip("/") for i, url in enumerate(urls)}
