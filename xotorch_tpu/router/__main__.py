"""CLI: `python -m xotorch_tpu.router` — the SLO-driven front door.

  python -m xotorch_tpu.router --port 52400 \
      --replica http://127.0.0.1:52415 --replica http://127.0.0.1:52416

Each --replica is one independent ring's OpenAI API base URL (any node of
that ring — every node serves the rolled-up /v1/alerts and /v1/queue).
With --fleet-template the replica set instead comes from a fleet template
file (see xotorch_tpu/fleet) and the router runs the elastic controller:
crash respawn, queue-pressure scale-up, drain-based scale-down — with
actuation gated behind the XOT_FLEET_LEASE_PATH lease so N routers can
share one template (all route, one acts). The router serves
/v1/chat/completions with session/prefix-affinity placement, drains
replicas on their own firing SLO alerts, probes them back to health with
canary completions, optionally hedges slow requests
(XOT_ROUTER_HEDGE_PCT), and reports at /v1/router.
Tunables are the XOT_ROUTER_* / XOT_FLEET_* knobs (README knob reference).
"""
from __future__ import annotations

import argparse
import asyncio
import signal
import sys


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(
    prog="python -m xotorch_tpu.router",
    description="OpenAI-compatible front door over N independent ring replicas: "
                "affinity + load routing, admission-aware spill, alert-driven "
                "replica drain/probe/readmit, elastic fleet control, hedging.")
  parser.add_argument("--replica", action="append", default=None,
                      help="replica API base URL (repeatable, one per ring); "
                           "not needed with --fleet-template")
  parser.add_argument("--fleet-template", default=None,
                      help="fleet template JSON: the slot universe the elastic "
                           "controller may spawn/retire (enables the controller)")
  parser.add_argument("--router-id", default="router",
                      help="this router's identity for the actuation lease and "
                           "its flight recorder (unique per router in HA)")
  parser.add_argument("--host", default="0.0.0.0")
  parser.add_argument("--port", type=int, default=52400)
  args = parser.parse_args(argv)
  if not args.replica and not args.fleet_template:
    parser.error("need --replica (repeatable) or --fleet-template")

  from xotorch_tpu.router.app import RouterApp

  async def run():
    router = RouterApp(args.replica or [], fleet_template=args.fleet_template,
                       router_id=args.router_id)
    runner = await router.run(host=args.host, port=args.port)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
      try:
        loop.add_signal_handler(sig, stop.set)
      except NotImplementedError:
        pass  # platforms without signal handler support (tests drive stop())
    await stop.wait()
    await router.stop()
    await runner.cleanup()

  asyncio.run(run())
  return 0


if __name__ == "__main__":
  sys.exit(main())
