"""CLI: `python -m xotorch_tpu.router` — the SLO-driven front door.

  python -m xotorch_tpu.router --port 52400 \
      --replica http://127.0.0.1:52415 --replica http://127.0.0.1:52416

Each --replica is one independent ring's OpenAI API base URL (any node of
that ring — every node serves the rolled-up /v1/alerts and /v1/queue).
The router serves /v1/chat/completions with session/prefix-affinity
placement, drains replicas on their own firing SLO alerts, probes them
back to health with canary completions, and reports at /v1/router.
Tunables are the XOT_ROUTER_* knobs (see the README knob reference).
"""
from __future__ import annotations

import argparse
import asyncio
import signal
import sys


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(
    prog="python -m xotorch_tpu.router",
    description="OpenAI-compatible front door over N independent ring replicas: "
                "affinity + load routing, admission-aware spill, alert-driven "
                "replica drain/probe/readmit.")
  parser.add_argument("--replica", action="append", required=True,
                      help="replica API base URL (repeatable, one per ring)")
  parser.add_argument("--host", default="0.0.0.0")
  parser.add_argument("--port", type=int, default=52400)
  args = parser.parse_args(argv)

  from xotorch_tpu.router.app import RouterApp

  async def run():
    router = RouterApp(args.replica)
    runner = await router.run(host=args.host, port=args.port)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
      try:
        loop.add_signal_handler(sig, stop.set)
      except NotImplementedError:
        pass  # platforms without signal handler support (tests drive stop())
    await stop.wait()
    await router.stop()
    await runner.cleanup()

  asyncio.run(run())
  return 0


if __name__ == "__main__":
  sys.exit(main())
