"""The router process: one OpenAI endpoint over N independent replicas.

An asyncio aiohttp proxy that (1) places each chat completion on a replica
by session/prefix affinity with queue-depth-aware spill (`router.route`),
(2) pre-announces queued prompts to the target replica's `/v1/prefetch` so
the host-tier warm-prefix restore overlaps the queue wait, and (3) runs
the alert-driven replica lifecycle: a poll loop reads each replica's
`/v1/alerts` and `/v1/queue` (the admission compact riding the metrics
rollup) every `XOT_ROUTER_POLL_S`, feeds `ReplicaLifecycle`, sends
synthetic canary completions to probing replicas, and records every
transition in the router's own flight recorder (served at
`/v1/debug/flight` exactly like a node's).

The router holds no model state and shares nothing with the replicas but
HTTP — a replica failure domain never reaches the router beyond a drained
entry in its table (arXiv 2004.13336's replica-sharding argument).
"""
from __future__ import annotations

import asyncio
import json
import time
from collections import deque
from typing import Dict, List, Optional

from aiohttp import ClientSession, ClientTimeout, web

from xotorch_tpu.orchestration.flight import FlightRecorder
from xotorch_tpu.router import (
  ReplicaLifecycle, fleet_trailing_medians, hedge_delay_s, least_loaded,
  name_drift, prefix_key, replica_names, route,
)
from xotorch_tpu.utils import knobs
from xotorch_tpu.utils.helpers import DEBUG, spawn_detached

_POLL_TIMEOUT = ClientTimeout(total=5.0)
_PROBE_TIMEOUT = ClientTimeout(total=60.0)


def _passthrough_headers(upstream_headers) -> dict:
  """Response headers the router relays verbatim: Retry-After plus the
  OpenAI-style x-ratelimit-* family the replica's admission gate stamps —
  a client behind the router sees the same budget view it would see
  talking to the replica directly."""
  out = {}
  for key, value in upstream_headers.items():
    lower = key.lower()
    if lower == "retry-after" or lower.startswith("x-ratelimit-"):
      out[key] = value
  return out


class _Replica:
  """One replica's live view: lifecycle + the latest poll observations."""

  def __init__(self, name: str, url: str):
    self.name = name
    self.url = url
    self.lifecycle = ReplicaLifecycle(name)
    self.reachable = False
    # Latest /v1/queue admission compact; None until the FIRST successful
    # poll — an unknown load must rank as heavy, never as idle.
    self.queue: Optional[dict] = None
    self.active_requests = 0       # latest ring-visible inflight
    # Disaggregated serving role polled off /v1/queue (XOT_FABRIC_ROLE on
    # the replica): `prefill` replicas never enter the routable set — they
    # serve only as the prefill leg of a router-chained request.
    self.role = "mixed"
    self.firing = 0                # latest cluster-wide firing alert count
    self.suspect: Optional[str] = None
    # Latest /v1/history trailing compact (None until the replica serves
    # one) and the debounced differential-drift verdict: `drift_hit` is
    # the live per-poll comparison, `drift` the metric it has held on for
    # XOT_ROUTER_DRIFT_POLLS consecutive polls — the drain-eligible name.
    self.history: Optional[dict] = None
    self.history_at: Optional[float] = None  # router-clock receive time
    self.drift_hit: Optional[dict] = None
    self.drift_polls = 0
    self.drift: Optional[str] = None
    # Last name ever held (with its evidence), surviving the clear: the
    # live `drift` field empties once the trailing window forgets, so a
    # teardown-time scrape could otherwise never say WHO was named.
    self.drift_last: Optional[dict] = None
    self.drift_named_total = 0
    self.routed_total = 0
    self.spilled_to_total = 0
    self.relayed_429_total = 0
    self.probe_inflight = False
    # Unified liveness/observation streak: consecutive poll ticks where
    # the replica was unreachable OR a scrape of a reachable replica
    # failed. Observation loss and liveness loss are ONE signal — a
    # replica the router cannot see is a replica the router cannot trust,
    # and the fleet controller's dead-detector consumes exactly this.
    self.down_streak = 0
    self.scrape_failures_total = 0
    # Fleet-controller gates: `warming` holds a freshly spawned replica
    # out of rotation until its warm pre-announce lands; `retiring` holds
    # a scale-down target out while its in-flight work drains.
    self.warming = False
    self.retiring = False

  def view(self) -> dict:
    """The placement view `router.route` consumes. A replica whose queue
    endpoint has NEVER answered ranks as maximally loaded (fail closed):
    it can still win by affinity, but spill and 429 retries never steer
    extra traffic onto the one replica whose load is unknown."""
    if self.queue is None:
      return {"name": self.name, "queued": 1 << 30, "est_wait_s": 1e9}
    return {"name": self.name, "queued": int(self.queue.get("queued") or 0),
            "est_wait_s": float(self.queue.get("est_wait_s") or 0.0)}

  def snapshot(self) -> dict:
    return {
      **self.lifecycle.snapshot(),
      "url": self.url, "reachable": self.reachable, "role": self.role,
      "firing": self.firing, "suspect": self.suspect,
      "drift": self.drift, "drift_hit": self.drift_hit,
      "drift_last": self.drift_last,
      "drift_named_total": self.drift_named_total,
      "active_requests": self.active_requests,
      "queue": self.queue,
      "routed_total": self.routed_total,
      "spilled_to_total": self.spilled_to_total,
      "relayed_429_total": self.relayed_429_total,
      "down_streak": self.down_streak,
      "scrape_failures_total": self.scrape_failures_total,
      "warming": self.warming,
      "retiring": self.retiring,
    }


class RouterApp:
  def __init__(self, replica_urls: List[str],
               fleet_template: Optional[str] = None,
               router_id: str = "router"):
    self.router_id = router_id
    if fleet_template:
      # The template is the replica universe: active slots are expected
      # to be running, latent ones exist only as spawn capacity — but
      # every slot gets a table entry NOW, so scale-up never mutates the
      # routing table's shape (a latent slot is simply never reachable).
      from xotorch_tpu.fleet import load_template
      slot_urls = {s["name"]: str(s["url"]).rstrip("/")
                   for s in load_template(fleet_template)}
    else:
      slot_urls = replica_names(replica_urls)
    self.replicas: Dict[str, _Replica] = {
      name: _Replica(name, url) for name, url in slot_urls.items()
    }
    self.poll_s = max(0.2, knobs.get_float("XOT_ROUTER_POLL_S"))
    self.spill_depth = max(0, knobs.get_int("XOT_ROUTER_SPILL_DEPTH"))
    self.probe_tokens = max(1, knobs.get_int("XOT_ROUTER_PROBE_TOKENS"))
    self.drift_enabled = knobs.get_bool("XOT_ROUTER_DRIFT")
    self.drift_polls_required = max(1, knobs.get_int("XOT_ROUTER_DRIFT_POLLS"))
    self.drift_peer_ratio = max(0.01, knobs.get_float("XOT_DRIFT_PEER_RATIO"))
    self.drift_min_samples = max(1, knobs.get_int("XOT_DRIFT_MIN_SAMPLES"))
    self.proxy_timeout = ClientTimeout(
      total=max(5.0, knobs.get_float("XOT_ROUTER_TIMEOUT_S")))
    # Request hedging: XOT_ROUTER_HEDGE_PCT=0 (the default) disables it
    # entirely — the first forward is the plain _forward call, byte for
    # byte. The budget caps hedges at pct% of proxied requests so a sick
    # fleet can't hedge-storm itself into double load.
    self.hedge_pct = max(0.0, knobs.get_float("XOT_ROUTER_HEDGE_PCT"))
    self.hedge_factor = max(0.0, knobs.get_float("XOT_ROUTER_HEDGE_FACTOR"))
    self.hedge_min_s = max(0.0, knobs.get_float("XOT_ROUTER_HEDGE_MIN_S"))
    self.flight = FlightRecorder(node_id=router_id)
    self.proxied_total = 0
    self.no_replica_503_total = 0
    self.prefetch_announced_total = 0
    self.fabric_chained_total = 0
    self.fabric_chain_failures_total = 0
    self.hedges_fired_total = 0
    self.hedges_won_total = 0
    self.hedge_cancelled_total = 0
    # Invariant tripwire, zero by construction (exactly one attempt is
    # ever relayed per request): a nonzero value means a refactor let two
    # hedge attempts reach the client, and the soak verdict reds on it.
    self.hedge_both_streamed_total = 0
    # Recent prompt prefixes (the /v1/prefetch payload shape): what the
    # fleet controller pre-announces at a freshly spawned replica so it
    # enters rotation with its host tier already filling.
    self.recent_bodies: deque = deque(maxlen=32)
    self._session: Optional[ClientSession] = None
    self._poll_task = None
    self.fleet = None
    if fleet_template:
      from xotorch_tpu.fleet.controller import FleetController
      self.fleet = FleetController(self, fleet_template, router_id)

    self.app = web.Application(client_max_size=100 * 1024 * 1024)
    r = self.app.router
    r.add_post("/v1/chat/completions", self.handle_chat)
    r.add_post("/chat/completions", self.handle_chat)
    r.add_get("/healthcheck", self.handle_healthcheck)
    r.add_get("/v1/router", self.handle_router_status)
    r.add_get("/v1/debug/flight", self.handle_flight)
    # Read-only conveniences: answered by any routable replica, so OpenAI
    # clients pointed at the router keep working end to end.
    for path in ("/v1/models", "/models", "/v1/topology", "/modelpool"):
      r.add_get(path, self.handle_proxy_get)

  # -------------------------------------------------------------- lifecycle

  async def start(self) -> None:
    self._session = ClientSession()
    self._poll_task = spawn_detached(self._poll_loop())

  async def stop(self) -> None:
    if self._poll_task is not None:
      self._poll_task.cancel()
      try:
        await self._poll_task
      except asyncio.CancelledError:
        pass
      self._poll_task = None
    if self._session is not None:
      await self._session.close()
      self._session = None
    if self.fleet is not None:
      # Hand actuation to a surviving router NOW instead of after a TTL.
      self.fleet.lease.release()

  def routable(self) -> List[_Replica]:
    # Prefill-role replicas are deliberately excluded: they answer chat
    # completions with KV handles, not token streams, so client traffic
    # must never land on one directly. Warming (freshly spawned, warm
    # pre-announce still landing) and retiring (scale-down draining)
    # replicas are out of rotation by controller decree.
    return [r for r in self.replicas.values()
            if r.lifecycle.routable and r.reachable and r.role != "prefill"
            and not r.warming and not r.retiring]

  def prefill_replicas(self) -> List[_Replica]:
    return [r for r in self.replicas.values()
            if r.lifecycle.routable and r.reachable and r.role == "prefill"]

  # ------------------------------------------------------------ poll + probe

  async def _poll_one(self, rep: _Replica) -> None:
    """One replica's poll tick, plus the unified liveness/observation
    streak: a tick is CLEAN only when the healthcheck answered and every
    scrape of the reachable replica succeeded. Consecutive unclean ticks
    feed `down_streak` — the same signal for a dead process and for one
    that is alive but unobservable, which the fleet controller's
    dead-detector treats identically."""
    clean = await self._poll_endpoints(rep)
    rep.down_streak = 0 if clean else rep.down_streak + 1

  async def _poll_endpoints(self, rep: _Replica) -> bool:
    assert self._session is not None
    clean = True
    try:
      async with self._session.get(f"{rep.url}/healthcheck",
                                   timeout=_POLL_TIMEOUT) as resp:
        rep.reachable = resp.status == 200
    except Exception:
      rep.reachable = False
    if not rep.reachable:
      return False
    try:
      async with self._session.get(f"{rep.url}/v1/queue",
                                   timeout=_POLL_TIMEOUT) as resp:
        q = await resp.json()
      rep.queue = q.get("admission") or {}
      rep.active_requests = int(q.get("active_requests") or 0)
      rep.role = str(q.get("fabric_role") or "mixed")
    except Exception as e:
      # Fail CLOSED (same policy as the alerts poll below): keep the last
      # observed load view — zeroing it would make the replica whose queue
      # endpoint just timed out look like the LEAST loaded one and attract
      # the spill traffic it can least afford.
      clean = False
      rep.scrape_failures_total += 1
      if DEBUG >= 2:
        print(f"router: /v1/queue poll of {rep.name} failed: {e!r}")
    try:
      async with self._session.get(f"{rep.url}/v1/alerts",
                                   timeout=_POLL_TIMEOUT) as resp:
        al = await resp.json()
      cluster = al.get("cluster") or {}
      rep.firing = int(cluster.get("firing") or 0)
      suspect = None
      for row in cluster.get("active") or []:
        if row.get("suspect"):
          suspect = str(row["suspect"])
          break
      rep.suspect = suspect
    except Exception as e:
      # Fail CLOSED: a replica whose alerts endpoint errors while its
      # health check stays green keeps its LAST observed firing/suspect —
      # zeroing it here would promote a still-burning replica out of
      # draining (or never drain it) exactly when it is least trustworthy.
      clean = False
      rep.scrape_failures_total += 1
      if DEBUG >= 2:
        print(f"router: /v1/alerts poll of {rep.name} failed: {e!r}")
    if not self.drift_enabled:
      return clean
    try:
      async with self._session.get(f"{rep.url}/v1/history?compact=1",
                                   timeout=_POLL_TIMEOUT) as resp:
        h = await resp.json()
      rep.history = h.get("compact") if h.get("enabled") else None
      # Stamped on the ROUTER's monotonic clock: freshness must not trust
      # the replica's wall clock (cross-host skew would silently disable
      # — or never expire — this replica's drift evidence).
      rep.history_at = time.monotonic()
    except Exception as e:
      # Fail CLOSED like the polls above: keep the last trailing view.
      clean = False
      rep.scrape_failures_total += 1
      if DEBUG >= 2:
        print(f"router: /v1/history poll of {rep.name} failed: {e!r}")
    return clean

  async def _probe_one(self, rep: _Replica) -> None:
    """One synthetic canary completion against a probing replica. The model
    field is omitted so the replica serves its own default — the router
    needs no model registry of its own. The outcome is stamped at probe
    COMPLETION (a cold canary can take tens of seconds), so readmitted_at
    is never backdated and the flap window measures real elapsed time."""
    assert self._session is not None
    rep.probe_inflight = True
    try:
      body = {"messages": [{"role": "user", "content": "router canary probe"}],
              "max_tokens": self.probe_tokens, "temperature": 0}
      ok = False
      try:
        async with self._session.post(f"{rep.url}/v1/chat/completions", json=body,
                                      timeout=_PROBE_TIMEOUT) as resp:
          data = await resp.json()
          content = (data.get("choices") or [{}])[0].get("message", {}).get("content")
          ok = resp.status == 200 and bool(content)
      except Exception:
        ok = False
      now = time.monotonic()
      ev = rep.lifecycle.note_probe(ok, now)
      if ev is not None:  # the only probe-driven transition is readmission
        self.flight.record("replica.readmitted", None, replica=rep.name,
                           probes=rep.lifecycle.probes_required,
                           out_s=round(now - (rep.lifecycle.drained_at or now), 2))
        if DEBUG >= 0:
          print(f"router: replica {rep.name} readmitted after "
                f"{rep.lifecycle.probes_required} canaries")
    finally:
      rep.probe_inflight = False

  def _note_drift(self, rep: _Replica) -> None:
    """One poll tick of the differential-drift detector: compare this
    replica's trailing history gauges against the median of its HEALTHY
    reachable peers (replicas serving rendezvous-split traffic should
    perform identically), debounced over consecutive polls so one noisy
    tick never drains anyone. Evaluated for every reachable replica — a
    drained one must be able to CLEAR its name, or it could never
    readmit."""
    now = time.monotonic()

    def fresh(r: _Replica) -> Optional[dict]:
      # A compact that has stopped refreshing (the /v1/history poll keeps
      # failing while the lighter polls keep the replica reachable) is
      # history, not evidence: judging by it would freeze a named
      # drifter's polluted pre-drain view and block the name from EVER
      # clearing. Staleness is measured on the router's receive stamps —
      # never the replica's wall clock.
      if r.history is None or r.history_at is None \
          or now - r.history_at > max(10.0 * self.poll_s, 30.0):
        return None
      return r.history

    peers = []
    for r in self.replicas.values():
      if r is rep or not r.reachable or not r.lifecycle.routable:
        continue
      h = fresh(r)
      if h is not None:
        peers.append(h)
    if not peers:
      # No fresh reference fleet: no verdict either way. Fail CLOSED like
      # the poll-failure handlers — a confirmed name must not clear (and
      # readmit a still-rotten replica) just because the peers' history
      # polls went dark; only a real tracks-the-fleet verdict clears it.
      rep.drift_hit = None
      return
    hit = name_drift(fresh(rep),
                     fleet_trailing_medians(peers, min_n=self.drift_min_samples),
                     self.drift_peer_ratio,
                     min_n=self.drift_min_samples)
    rep.drift_hit = hit
    if hit is None:
      rep.drift_polls = 0
      rep.drift = None
      return
    # Single-suspect discipline: while any OTHER replica is out of
    # rotation the fleet median is not a steady reference — naming a
    # second chronic drifter then could take the whole fleet out, and the
    # overflow load a drain shifts onto survivors legitimately moves
    # their gauges. The debounce counter RESETS too: deviations observed
    # during (or before) the unsteady phase are load-shift artifacts, and
    # crediting them would let a survivor be named on the first steady
    # poll after a peer readmits — naming requires the deviation to hold
    # for XOT_ROUTER_DRIFT_POLLS consecutive STEADY polls.
    fleet_steady = all(r.lifecycle.state == "healthy"
                      for r in self.replicas.values() if r is not rep)
    if not fleet_steady:
      rep.drift_polls = 0
      return
    rep.drift_polls += 1
    if rep.drift_polls >= self.drift_polls_required and rep.drift is None:
      rep.drift = f"perf_drift:{hit['metric']}"
      rep.drift_last = {"name": rep.drift, "at": time.time(), **hit}
      rep.drift_named_total += 1
      self.flight.record("drift.replica", None, replica=rep.name,
                         metric=hit["metric"], value=hit["value"],
                         peer_median=hit["peer_median"],
                         worse_by=hit["worse_by"])
      if DEBUG >= 0:
        print(f"router: replica {rep.name} named {rep.drift} "
              f"({hit['value']} vs fleet median {hit['peer_median']})")

  async def _poll_loop(self) -> None:
    while True:
      await asyncio.sleep(self.poll_s)
      now = time.monotonic()
      try:
        await asyncio.gather(*(self._poll_one(r) for r in self.replicas.values()))
        if self.drift_enabled:
          for rep in self.replicas.values():
            if rep.reachable:
              self._note_drift(rep)
        for rep in self.replicas.values():
          inflight = rep.active_requests
          q = rep.queue or {}
          if q.get("max_inflight"):
            inflight = max(inflight, int(q.get("inflight") or 0))
          ev = rep.lifecycle.note_status(
            now, firing=rep.firing, suspect=rep.suspect or rep.drift,
            inflight=inflight, reachable=rep.reachable)
          if ev is not None:
            if ev["transition"] == "draining":
              self.flight.record("replica.draining", None, replica=rep.name,
                                 reason=ev["reason"])
            elif ev["transition"] == "probing":
              self.flight.record("replica.probing", None, replica=rep.name)
            if DEBUG >= 0:
              print(f"router: replica {rep.name} -> {ev['transition']}"
                    f" ({ev.get('reason') or ''})")
          if rep.lifecycle.state == "probing" and rep.reachable and not rep.probe_inflight:
            spawn_detached(self._probe_one(rep))
        if self.fleet is not None:
          # After lifecycle: the controller consumes the streaks and
          # lifecycle states this tick just settled. tick() never raises.
          self.fleet.tick(now)
      except Exception as e:
        if DEBUG >= 1:
          print(f"router poll error: {e!r}")

  # ----------------------------------------------------------------- routes

  async def handle_healthcheck(self, request):
    return web.json_response({"status": "ok", "replicas": len(self.replicas),
                              "routable": len(self.routable())})

  async def handle_router_status(self, request):
    return web.json_response({
      "router_id": self.router_id,
      "replicas": {name: rep.snapshot() for name, rep in self.replicas.items()},
      "routable": [r.name for r in self.routable()],
      "proxied_total": self.proxied_total,
      "no_replica_503_total": self.no_replica_503_total,
      "prefetch_announced_total": self.prefetch_announced_total,
      "fabric_chained_total": self.fabric_chained_total,
      "fabric_chain_failures_total": self.fabric_chain_failures_total,
      "hedges_fired_total": self.hedges_fired_total,
      "hedges_won_total": self.hedges_won_total,
      "hedge_cancelled_total": self.hedge_cancelled_total,
      "hedge_both_streamed_total": self.hedge_both_streamed_total,
      "scrape_failures_total": sum(r.scrape_failures_total
                                   for r in self.replicas.values()),
      "prefill_replicas": [r.name for r in self.prefill_replicas()],
      "drains_total": sum(r.lifecycle.drains_total for r in self.replicas.values()),
      "readmits_total": sum(r.lifecycle.readmits_total for r in self.replicas.values()),
      "drift_named_total": sum(r.drift_named_total for r in self.replicas.values()),
      "poll_s": self.poll_s, "spill_depth": self.spill_depth,
      "fleet": self.fleet.status() if self.fleet is not None else None,
    })

  async def handle_flight(self, request):
    body = {"node_id": "router", **self.flight.stats(),
            "snapshots": self.flight.snapshots(), "events": self.flight.tail(0)}
    return web.json_response(body)

  async def handle_proxy_get(self, request):
    targets = self.routable() or [r for r in self.replicas.values() if r.reachable]
    if not targets:
      return web.json_response({"detail": "no reachable replica"}, status=503)
    assert self._session is not None
    rep = targets[0]
    try:
      async with self._session.get(f"{rep.url}{request.path_qs}",
                                   timeout=_POLL_TIMEOUT) as resp:
        return web.Response(body=await resp.read(), status=resp.status,
                            content_type=resp.content_type)
    except Exception as e:
      return web.json_response({"detail": f"replica {rep.name} failed: {e!r}"},
                               status=502)

  def _announce_prefetch(self, rep: _Replica, body: dict,
                         force: bool = False) -> None:
    """PRESERVE pre-announce: ship the request's messages to the target's
    /v1/prefetch so its host tier can start the warm-prefix restore while
    the request is queued (there, or still in flight here). Only fired
    when the target actually has a wait (inflight at cap or queue
    non-empty) — an immediately admitted request reuses its prefix through
    the normal path at no extra cost. `force` overrides the wait check for
    targets whose local warm set is presumed NOT to cover this prefix: a
    spill target (the affinity owner holds the warm KV, so the prefetch is
    what triggers the cross-replica fabric fetch) and a freshly readmitted
    replica (whatever it held pre-drain is stale or evicted)."""
    q = rep.queue or {}
    waiting = (int(q.get("queued") or 0) > 0
               or (int(q.get("max_inflight") or 0) > 0
                   and int(q.get("inflight") or 0) >= int(q.get("max_inflight") or 0)))
    readmit_at = rep.lifecycle.readmitted_at
    fresh_readmit = (readmit_at is not None
                     and time.monotonic() - readmit_at < 10.0 * self.poll_s)
    if not (force or waiting or fresh_readmit) or self._session is None:
      return

    async def announce():
      payload = {k: body[k] for k in ("model", "messages", "tools") if k in body}
      try:
        async with self._session.post(f"{rep.url}/v1/prefetch", json=payload,
                                      timeout=_POLL_TIMEOUT) as resp:
          if resp.status == 202:
            self.prefetch_announced_total += 1
      except Exception as e:
        if DEBUG >= 2:
          print(f"router prefetch announce to {rep.name} failed: {e!r}")

    spawn_detached(announce())

  def spawn_warm_announce(self, rep: _Replica, n: int) -> None:
    """The fleet controller's warm cold-start leg: post the last `n`
    recent prompt prefixes to a freshly booted replica's /v1/prefetch
    (each one chains into the host-tier restore and, where a sibling
    holds the KV, the PR 18 fabric fetch) and only then clear `warming`
    so the replica enters rotation with work already warming it. Every
    failure is absorbed — the announce can only make the replica warmer,
    never keep it out of rotation."""
    bodies = list(self.recent_bodies)[-n:] if n > 0 else []

    async def warm():
      try:
        for payload in bodies:
          try:
            async with self._session.post(f"{rep.url}/v1/prefetch", json=payload,
                                          timeout=_POLL_TIMEOUT) as resp:
              if resp.status == 202:
                self.prefetch_announced_total += 1
          except Exception as e:
            if DEBUG >= 2:
              print(f"router: warm announce to {rep.name} failed: {e!r}")
      finally:
        rep.warming = False

    if self._session is None:
      rep.warming = False
      return
    spawn_detached(warm())

  async def _chain_prefill(self, rep: _Replica, body: dict) -> None:
    """Disaggregated serving: run the prompt on a prefill-role replica
    first, then pre-announce the resulting KV handle at the decode target
    (`/v1/kv/offer`) so its fabric consult imports the finished prefill
    instead of recomputing it. Awaited — the offer must land before the
    decode forward's prefix probe runs, or the decode replica would race
    its own cold prefill against the transfer. EVERY failure (no prefill
    replica, prefill error, offer rejected) degrades to a plain forward:
    the chain changes where prefill runs, never whether a request can."""
    pre = next((r for r in self.prefill_replicas() if r is not rep), None)
    if pre is None or self._session is None:
      return
    payload = {k: body[k] for k in ("model", "messages", "tools") if k in body}
    payload["stream"] = False
    try:
      async with self._session.post(f"{pre.url}/v1/chat/completions",
                                    json=payload,
                                    timeout=self.proxy_timeout) as resp:
        handle = await resp.json() if resp.status == 200 else None
      if (not isinstance(handle, dict) or handle.get("object") != "kv.handle"
          or not handle.get("tokens")):
        raise ValueError(f"no kv.handle from {pre.name}")
      pre.routed_total += 1
      offer = {"model": body.get("model"), "tokens": handle["tokens"],
               "length": handle.get("length"), "nbytes": handle.get("nbytes"),
               "url": pre.url}
      async with self._session.post(f"{rep.url}/v1/kv/offer", json=offer,
                                    timeout=_POLL_TIMEOUT) as oresp:
        if oresp.status != 202:
          raise ValueError(f"offer to {rep.name} rejected ({oresp.status})")
      self.fabric_chained_total += 1
    except Exception as e:
      self.fabric_chain_failures_total += 1
      if DEBUG >= 1:
        print(f"router: prefill chain via {pre.name} failed "
              f"(decode target prefills cold): {e!r}")

  def _no_replica_503(self):
    self.no_replica_503_total += 1
    return web.json_response(
      {"error": {"type": "server_error", "code": "no_replica",
                 "message": "no healthy replica is accepting traffic"}},
      status=503, headers={"Retry-After": str(int(self.poll_s * 2) or 1)})

  async def handle_chat(self, request):
    try:
      body = await request.json()
    except (json.JSONDecodeError, UnicodeDecodeError):
      return web.json_response(
        {"error": {"type": "invalid_request_error", "message": "body must be JSON"}},
        status=400)
    if not isinstance(body, dict):
      return web.json_response(
        {"error": {"type": "invalid_request_error",
                   "message": "body must be a JSON object"}}, status=400)
    views = [r.view() for r in self.routable()]
    picked = route(prefix_key(body), views, self.spill_depth)
    if picked is None:
      return self._no_replica_503()
    name, spilled = picked
    rep = self.replicas[name]
    rep.routed_total += 1
    if spilled:
      rep.spilled_to_total += 1
    self.proxied_total += 1
    # Remember the prompt prefix for the fleet controller's warm
    # cold-start pre-announce (a respawned replica gets the recent
    # working set pushed at it before entering rotation).
    self.recent_bodies.append(
      {k: body[k] for k in ("model", "messages", "tools") if k in body})
    # A spill target is, by construction, NOT the affinity owner of this
    # prefix — force the pre-announce so its fabric consult pulls the warm
    # KV from the sibling that is.
    self._announce_prefetch(rep, body, force=spilled)
    await self._chain_prefill(rep, body)
    resp = await self._forward_hedged(rep, body, request)
    if resp is None:
      # Replica shed it (429): one spill retry on the least-loaded OTHER
      # routable replica before the 429 reaches the client — by queue
      # depth, NOT affinity (the affinity target just proved it is full;
      # re-hashing could land on another saturated replica while a free
      # one sits idle).
      # Re-filter against the LIVE routable set: the poll loop may have
      # drained a replica while the first forward was in flight, and a
      # retry must not hand new traffic to a replica that is now out.
      routable_now = {r.name for r in self.routable()}
      others = [v for v in views if v["name"] != name and v["name"] in routable_now]
      least = least_loaded(others)
      if least is not None:
        alt_rep = self.replicas[str(least["name"])]
        alt_rep.routed_total += 1
        alt_rep.spilled_to_total += 1
        self._announce_prefetch(alt_rep, body, force=True)
        resp = await self._forward(alt_rep, body, request)
      if resp is None:
        # Final attempt, relaying the 429 if it still sheds — but a request
        # ADMITTED here keeps full streaming semantics (a real forward, not
        # a buffered re-read). Routability is re-read NOW (the alternate
        # attempt may have outlived another poll tick) and the forward is
        # accounted in routed_total like every other attempt, so a drained
        # replica can neither serve this request nor serve it invisibly to
        # the routed-while-out tracker.
        final_now = {r.name for r in self.routable()}
        final_rep = rep if rep.name in final_now else None
        if final_rep is None:
          fallback = least_loaded([r.view() for r in self.routable()])
          final_rep = self.replicas[str(fallback["name"])] if fallback else None
        if final_rep is None:
          return self._no_replica_503()
        final_rep.routed_total += 1
        resp = await self._forward(final_rep, body, request, final=True)
        if getattr(resp, "status", None) == 429:
          final_rep.relayed_429_total += 1
    return resp

  async def _forward(self, rep: _Replica, body: dict, request, final: bool = False):
    """Proxy one completion to a replica. Returns the prepared response, or
    None when the replica answered 429 and `final` is False (the caller
    may retry elsewhere); `final` relays the 429 to the client instead.
    Streaming responses are relayed chunk-for-chunk as they arrive."""
    if body.get("stream"):
      return await self._relay_stream(rep, body, request, allow_429=final)
    return await self._relay_json(rep, body, request, allow_429=final)

  # ---------------------------------------------------------------- hedging

  def _hedge_delay(self) -> float:
    """The fleet-derived hedge trigger: XOT_ROUTER_HEDGE_FACTOR x the
    median trailing p99 across routable replicas' /v1/history compacts,
    floored at XOT_ROUTER_HEDGE_MIN_S."""
    return hedge_delay_s((r.history for r in self.routable()
                          if r.history is not None),
                         self.hedge_factor, self.hedge_min_s)

  async def _forward_hedged(self, rep: _Replica, body: dict, request):
    """The FIRST forward attempt, with tail hedging. If the primary has
    produced no byte (streaming: no SSE chunk; non-streaming: no response)
    after the p99-derived delay, the request is duplicated at the
    least-loaded OTHER routable replica; the first attempt to produce a
    byte wins and the loser is cancelled server-side by closing its
    upstream connection (the replica's handler `finally` aborts the
    request — the existing disconnect path). Never hedges after the first
    streamed byte BY CONSTRUCTION: an attempt only settles once its first
    byte arrived, and the hedge only fires while the primary is
    unsettled. XOT_ROUTER_HEDGE_PCT=0 (default) is the plain _forward,
    byte for byte; the pct budget caps hedges against proxied requests."""
    if self.hedge_pct <= 0:
      return await self._forward(rep, body, request)
    others = [r for r in self.routable() if r is not rep]
    budget_ok = (self.hedges_fired_total + 1
                 <= self.hedge_pct / 100.0 * max(1, self.proxied_total))
    if not others or not budget_ok:
      return await self._forward(rep, body, request)
    streaming = bool(body.get("stream"))
    delay = self._hedge_delay()
    primary = spawn_detached(self._open_attempt(rep, body, streaming))
    done, _ = await asyncio.wait({primary}, timeout=delay)
    if done:  # settled (first byte, shed, or error) before the delay
      return await self._settle_attempts(None, [(primary, rep)], request)
    rid = f"hedge-{self.hedges_fired_total}-{int(time.time() * 1000) % 1000000}"
    alt = self.replicas[str(least_loaded([r.view() for r in others])["name"])]
    self.hedges_fired_total += 1
    alt.routed_total += 1
    self.flight.record("hedge.fired", rid, primary=rep.name, alt=alt.name,
                       delay_s=round(delay, 3))
    alt_task = spawn_detached(self._open_attempt(alt, body, streaming))
    return await self._settle_attempts(rid, [(primary, rep), (alt_task, alt)],
                                       request)

  async def _open_attempt(self, rep: _Replica, body: dict, streaming: bool) -> dict:
    """POST one attempt and wait for its FIRST byte without touching the
    client response: a streaming 200 settles on its first SSE chunk,
    everything else (JSON completions, 429s, error statuses) on the full
    body — small by construction. The returned dict is relayed or aborted
    by the caller; on error the upstream response is released here."""
    assert self._session is not None
    resp = await self._session.post(f"{rep.url}/v1/chat/completions", json=body,  # xotlint: disable=http-client-hygiene (attempt failures are consumed by _settle_attempts via task.exception, never raised to the client)
                                    timeout=self.proxy_timeout)
    try:
      if not streaming or resp.status != 200:
        data = await resp.read()
        return {"rep": rep, "resp": resp, "status": resp.status, "body": data,
                "streaming": False}
      first = await resp.content.readany()
      return {"rep": rep, "resp": resp, "status": resp.status, "first": first,
              "streaming": True}
    except BaseException:
      resp.close()
      raise

  async def _settle_attempts(self, rid: Optional[str], attempts, request):
    """Race the attempts to the first usable winner (opened, not a 429),
    abort every other attempt, and relay the winner. With a single
    attempt this reduces exactly to _forward's semantics: 429 -> None
    (spill retry), connect failure -> _connect_failed -> None, any other
    status relayed."""
    tasks = [t for t, _ in attempts]
    rep_of = {id(t): r for t, r in attempts}
    hedged = len(tasks) > 1
    pending = {t for t in tasks if not t.done()}
    settled = [t for t in tasks if t.done()]
    winner = None
    saw_429 = False
    last_fail = None
    while True:
      for t in (t for t in tasks if t in settled):
        if t.cancelled():
          continue
        if t.exception() is not None:
          last_fail = (rep_of[id(t)], t.exception())
          continue
        att = t.result()
        if winner is not None:
          self._abort_attempt(rid, att, hedged)
        elif att["status"] == 429:
          saw_429 = True
          att["resp"].release()
        else:
          winner = att
      settled = []
      if winner is not None or not pending:
        break
      done, pending = await asyncio.wait(pending,
                                         return_when=asyncio.FIRST_COMPLETED)
      settled = list(done)
    for t in pending:
      self._cancel_task(rid, t, hedged)
    if winner is None:
      if saw_429:
        return None
      rep, exc = last_fail if last_fail else (attempts[0][1],
                                              RuntimeError("no attempt ran"))
      return self._connect_failed(rep, exc, final=False)
    if hedged and winner["rep"] is not attempts[0][1]:
      self.hedges_won_total += 1
      self.flight.record("hedge.won", rid, winner=winner["rep"].name,
                         primary=attempts[0][1].name)
    return await self._relay_attempt(winner, request)

  def _abort_attempt(self, rid: Optional[str], att: dict, hedged: bool) -> None:
    """Server-side cancel of a losing attempt: closing the upstream
    connection mid-stream (or before the body is drained) trips the
    replica handler's disconnect path, which aborts the request and frees
    its device state — the same abort path a vanished client takes."""
    try:
      att["resp"].close()
    except Exception:
      pass
    if hedged:
      self.hedge_cancelled_total += 1
      self.flight.record("hedge.cancelled", rid, loser=att["rep"].name)
      self.flight.freeze(rid, reason="hedge.cancelled")

  def _cancel_task(self, rid: Optional[str], task, hedged: bool) -> None:
    """Cancel a still-unsettled attempt. The task owns its upstream
    response until it returns, so cancellation closes the socket either
    via the open_attempt error path or the done-callback below (for the
    race where it settled between our check and the cancel)."""
    if task.done():
      if not task.cancelled() and task.exception() is None:
        self._abort_attempt(rid, task.result(), hedged)
      return
    task.cancel()

    def _reap(t):
      try:
        if not t.cancelled() and t.exception() is None:
          t.result()["resp"].close()
      except Exception:
        pass

    task.add_done_callback(_reap)
    if hedged:
      self.hedge_cancelled_total += 1
      self.flight.record("hedge.cancelled", rid, loser="(unsettled)")
      self.flight.freeze(rid, reason="hedge.cancelled")

  async def _relay_attempt(self, att: dict, request):
    """Relay the winning attempt to the client. Exactly one attempt per
    request reaches this point; the guard counts (never silently drops)
    any violation — hedge_both_streamed_total is zero-toleranced by the
    fleet soak."""
    if att.get("relayed"):
      self.hedge_both_streamed_total += 1
      return None
    att["relayed"] = True
    resp = att["resp"]
    if not att["streaming"]:
      resp.release()
      return web.Response(body=att["body"], status=att["status"],
                          content_type=resp.content_type,
                          headers=_passthrough_headers(resp.headers))
    try:
      response = web.StreamResponse(status=200, headers={
        "Content-Type": resp.headers.get("Content-Type", "text/event-stream"),
        "Cache-Control": "no-cache",
        "Access-Control-Allow-Origin": "*",
        **_passthrough_headers(resp.headers),
      })
      await response.prepare(request)
      if att.get("first"):
        await response.write(att["first"])
      async for chunk in resp.content.iter_any():
        await response.write(chunk)
      await response.write_eof()
      return response
    finally:
      resp.release()

  def _connect_failed(self, rep: _Replica, e: Exception, final: bool):
    """A request that never reached the replica (connect refused/reset
    before any byte) is safe to retry elsewhere: mark the replica
    unreachable NOW (the next poll/lifecycle tick drains it; the final-
    attempt routability re-check skips it) and return None so the caller's
    retry machinery engages — a crash between poll ticks must fail over
    like a 429, not surface as a 502 while a healthy replica sits idle.
    On the FINAL attempt there is nowhere left to go: answer 502."""
    rep.reachable = False
    if DEBUG >= 1:
      print(f"router: forward to {rep.name} failed: {e!r}")
    if final:
      return web.json_response(
        {"error": {"type": "server_error",
                   "message": f"replica {rep.name} failed: {e!r}"}}, status=502)
    return None

  async def _relay_json(self, rep: _Replica, body: dict, request, allow_429: bool):
    assert self._session is not None
    try:
      async with self._session.post(f"{rep.url}/v1/chat/completions", json=body,
                                    timeout=self.proxy_timeout) as resp:
        if resp.status == 429 and not allow_429:
          return None
        return web.Response(body=await resp.read(), status=resp.status,
                            content_type=resp.content_type,
                            headers=_passthrough_headers(resp.headers))
    except Exception as e:
      # allow_429 is set exactly on the final attempt (see _forward).
      return self._connect_failed(rep, e, final=allow_429)

  async def _relay_stream(self, rep: _Replica, body: dict, request,
                          allow_429: bool = False):
    """SSE pass-through. The upstream connection is held for the stream's
    life; the client response is prepared LAZILY on the first upstream
    byte, so a pre-stream 429 can still return None for the spill retry
    (or, with allow_429, relay the 429 JSON — a shed request never
    streamed anything)."""
    assert self._session is not None
    try:
      upstream = await self._session.post(f"{rep.url}/v1/chat/completions",
                                          json=body, timeout=self.proxy_timeout)
    except Exception as e:
      # allow_429 is set exactly on the final attempt (see _forward).
      return self._connect_failed(rep, e, final=allow_429)
    try:
      if upstream.status == 429 and not allow_429:
        return None
      if upstream.status != 200:
        return web.Response(body=await upstream.read(), status=upstream.status,
                            content_type=upstream.content_type,
                            headers=_passthrough_headers(upstream.headers))
      response = web.StreamResponse(status=200, headers={
        "Content-Type": upstream.headers.get("Content-Type", "text/event-stream"),
        "Cache-Control": "no-cache",
        "Access-Control-Allow-Origin": "*",
        **_passthrough_headers(upstream.headers),
      })
      await response.prepare(request)
      async for chunk in upstream.content.iter_any():
        await response.write(chunk)
      await response.write_eof()
      return response
    finally:
      upstream.release()

  async def run(self, host: str = "0.0.0.0", port: int = 52400):
    await self.start()
    runner = web.AppRunner(self.app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    if DEBUG >= 0:
      print(f"xot router on http://{host}:{port} over "
            f"{len(self.replicas)} replica(s): "
            + ", ".join(f"{n}={r.url}" for n, r in self.replicas.items()))
    return runner
