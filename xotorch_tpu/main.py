"""The `xot` CLI: construct the object graph and run a peer.

Parity: /root/reference/xotorch/main.py:73-402 — subcommands run|eval|train,
discovery module selection (udp|manual), node/API wiring, event plumbing
(preemptive shard load on remote prompt-start, throttled download-progress
broadcast), signal handling, one-shot run/train/eval flows.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import time
import uuid
from functools import partial
from pathlib import Path

from xotorch_tpu import VERSION
from xotorch_tpu.api.chatgpt_api import ChatGPTAPI
from xotorch_tpu.inference.engine import get_inference_engine, inference_engine_classes
from xotorch_tpu.inference.shard import Shard
from xotorch_tpu.inference.tokenizers import resolve_tokenizer
from xotorch_tpu.models.registry import build_base_shard, get_repo, model_cards
from xotorch_tpu.networking.grpc.peer_handle import GRPCPeerHandle
from xotorch_tpu.networking.grpc.server import GRPCServer
from xotorch_tpu.orchestration.node import Node
from xotorch_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
from xotorch_tpu.utils import knobs
from xotorch_tpu.utils.helpers import (
  DEBUG,
  find_available_port,
  get_all_ip_addresses_and_interfaces,
  get_or_create_node_id,
  shutdown,
  spawn_detached,
)


def build_parser() -> argparse.ArgumentParser:
  parser = argparse.ArgumentParser(prog="xot", description="xotorch_tpu: TPU-native distributed LLM runtime")
  parser.add_argument("command", nargs="?", choices=["run", "eval", "train"], help="one-shot command")
  parser.add_argument("model_name", nargs="?", help="model id (see models registry)")
  parser.add_argument("--version", action="version", version=f"xot {VERSION}")
  parser.add_argument("--node-id", type=str, default=None)
  parser.add_argument("--node-host", type=str, default="0.0.0.0")
  parser.add_argument("--node-port", type=int, default=None)
  parser.add_argument("--listen-port", type=int, default=5678, help="UDP discovery listen port")
  parser.add_argument("--broadcast-port", type=int, default=5678)
  parser.add_argument("--discovery-module", type=str, choices=["udp", "manual"], default="udp")
  parser.add_argument("--discovery-timeout", type=int, default=30)
  parser.add_argument("--discovery-config-path", type=str, default=None)
  parser.add_argument("--wait-for-peers", type=int, default=0)
  parser.add_argument("--inference-engine", type=str, default="jax", help="jax | dummy")
  parser.add_argument("--chatgpt-api-port", type=int, default=52415)
  parser.add_argument("--chatgpt-api-response-timeout", type=int, default=90)
  parser.add_argument("--max-generate-tokens", type=int, default=1024)
  parser.add_argument("--default-temp", type=float, default=0.6)
  parser.add_argument("--default-top-k", type=int, default=35)
  parser.add_argument("--system-prompt", type=str, default=None)
  parser.add_argument("--default-model", type=str, default=None)
  parser.add_argument("--disable-tui", action="store_true")
  parser.add_argument("--chat-tui", action="store_true",
                      help="terminal chat mode with live tok/s (parity ref main.py:100,380-381)")
  parser.add_argument("--prompt", type=str, default="Who are you?")
  parser.add_argument("--run-gc", action="store_true", help="run garbage collection after each request")
  parser.add_argument("--models-seed-dir", type=str, default=None)
  # train flags (parity main.py:78-82)
  parser.add_argument("--data", type=str, default="xotorch_tpu/train/data/lora")
  parser.add_argument("--iters", type=int, default=100)
  parser.add_argument("--batch-size", type=int, default=1)
  parser.add_argument("--sequence-length", type=int, default=512)
  parser.add_argument("--save-every", type=int, default=5)
  parser.add_argument("--save-checkpoint-dir", type=str, default="checkpoints")
  parser.add_argument("--resume-checkpoint", type=str, default=None)
  parser.add_argument("--lora-rank", type=int, default=0,
                      help="attach rank-r LoRA adapters; train updates only them (<1%% of params)")
  parser.add_argument("--quantize", type=str, default=None, choices=["int8", "int4"],
                      help="weight-only quantization: int8 halves HBM bytes/token (~2x decode); "
                           "int4 quarters them (group-wise, embeddings/experts stay int8)")
  parser.add_argument("--kv-quantize", type=str, default=None, choices=["int8"],
                      help="int8 KV cache: half the cache bandwidth + HBM per resident token "
                           "(long-context serving)")
  parser.add_argument("--serve-tp", type=int, default=None,
                      help="tensor-parallel width over this peer's local chips "
                           "(default: all local chips on real TPU; 0/1 disables)")
  parser.add_argument("--serve-sp", type=int, default=None,
                      help="sequence-parallel width for long-prompt prefill: the from-zero "
                           "segment ring-attends over this many local chips (composes with "
                           "--serve-tp; power of two)")
  parser.add_argument("--serve-ep", type=int, default=None,
                      help="expert-parallel width for MoE models: expert weights distribute "
                           "over this many local chips' HBM, each computing its resident "
                           "experts (composes with --serve-tp; must divide the expert count)")
  parser.add_argument("--draft-model", type=str, default=None,
                      help="model id to greedy-draft speculative tokens with (must share the "
                           "target's tokenizer, e.g. llama-3.2-1b for llama-3.1-70b); the "
                           "target verifies each draft in one forward. Implies speculation "
                           "on (depth XOT_SPECULATE, default 8)")
  parser.add_argument("--adapters", type=str, default=None,
                      help="multi-LoRA serving registry: 'name=/path/to/adapter,name2=/dir'. "
                           "Requests select an adapter via the model id 'base@name'; all "
                           "adapters share one resident base (adapter-only checkpoints from "
                           "--lora-rank training)")
  return parser


def build_node(args) -> tuple:
  node_id = args.node_id or get_or_create_node_id()
  node_port = args.node_port or find_available_port()
  if getattr(args, "lora_rank", 0):
    # The engine reads this at shard-load time (every peer must agree, so the
    # train CLI's value rides the env into locally spawned engines; remote
    # peers set their own flag).
    os.environ["XOT_LORA_RANK"] = str(args.lora_rank)
  if getattr(args, "quantize", None):
    os.environ["XOT_QUANTIZE"] = args.quantize
  if getattr(args, "kv_quantize", None):
    os.environ["XOT_KV_QUANT"] = args.kv_quantize
  if getattr(args, "draft_model", None):
    os.environ["XOT_DRAFT_MODEL"] = args.draft_model
  if getattr(args, "adapters", None):
    os.environ["XOT_ADAPTERS"] = args.adapters
  if getattr(args, "serve_tp", None) is not None:
    os.environ["XOT_SERVE_TP"] = str(args.serve_tp)
  if getattr(args, "serve_sp", None) is not None:
    os.environ["XOT_SERVE_SP"] = str(args.serve_sp)
  if getattr(args, "serve_ep", None) is not None:
    os.environ["XOT_SERVE_EP"] = str(args.serve_ep)

  # Multi-host slice seam (SURVEY §2.9 north-star: no gRPC intra-slice):
  # when the launcher provides slice membership (XOT_COORDINATOR/XOT_MULTIHOST),
  # the co-hosted processes join one JAX distributed runtime BEFORE any
  # device use, so every serving/training mesh spans the whole slice and its
  # collectives ride ICI. The gRPC ring then connects only slice leaders.
  from xotorch_tpu.parallel.multihost import init_multihost, multihost_requested
  if multihost_requested():
    n_proc, rank = init_multihost()
    print(f"multi-host slice: process {rank}/{n_proc}")

  from xotorch_tpu.download import NoopShardDownloader
  from xotorch_tpu.download.hf_shard_download import HFShardDownloader

  engine_name = args.inference_engine
  if engine_name == "dummy":
    downloader = NoopShardDownloader()
    # A dummy peer has no use for accelerator capabilities; skip the (slow on
    # tunneled TPUs) JAX probe so CLI dry runs start instantly.
    os.environ.setdefault("XOT_SKIP_JAX_PROBE", "1")
  else:
    downloader = HFShardDownloader()
  engine = get_inference_engine(engine_name, downloader)
  engine_classname = type(engine).__name__

  def create_peer_handle(peer_id, addr, desc, caps):
    return GRPCPeerHandle(peer_id, addr, desc, caps)

  if args.discovery_module == "udp":
    from xotorch_tpu.networking.udp.discovery import UDPDiscovery
    discovery = UDPDiscovery(
      node_id, node_port, args.listen_port, args.broadcast_port,
      create_peer_handle, discovery_timeout=args.discovery_timeout,
    )
  else:
    from xotorch_tpu.networking.manual.discovery import ManualDiscovery
    if not args.discovery_config_path:
      raise SystemExit("--discovery-config-path is required with --discovery-module manual")
    discovery = ManualDiscovery(args.discovery_config_path, node_id, create_peer_handle)

  # The chat TUI owns the terminal — never run the Live topology layout under
  # it (same exclusion as the reference, main.py:158).
  topology_viz = None
  if not args.disable_tui and not args.chat_tui:
    from xotorch_tpu.viz.topology_viz import TopologyViz
    api_endpoints = [f"http://{ip}:{args.chatgpt_api_port}/v1/chat/completions"
                     for ip, _ in get_all_ip_addresses_and_interfaces()][:2]
    web_urls = [f"http://{ip}:{args.chatgpt_api_port}" for ip, _ in get_all_ip_addresses_and_interfaces()][:2]
    topology_viz = TopologyViz(chatgpt_api_endpoints=api_endpoints, web_chat_urls=web_urls)

  node = Node(
    node_id, None, engine, discovery, downloader,
    RingMemoryWeightedPartitioningStrategy(),
    max_generate_tokens=args.max_generate_tokens,
    default_sample_temp=args.default_temp,
    default_sample_top_k=args.default_top_k,
    topology_viz=topology_viz,
  )
  node.server = GRPCServer(node, args.node_host, node_port)

  api = ChatGPTAPI(
    node, engine_classname,
    response_timeout=args.chatgpt_api_response_timeout,
    default_model=args.default_model,
    system_prompt=args.system_prompt,
  )
  if topology_viz is not None:
    api.on_chat_completion_request = lambda req_id, _req, prompt: topology_viz.update_prompt(req_id, prompt)

  _wire_events(node, engine, engine_classname, topology_viz, downloader)
  return node, engine, engine_classname, api, topology_viz


def _wire_events(node: Node, engine, engine_classname: str, topology_viz, downloader) -> None:
  """Event plumbing (parity main.py:180-224)."""
  # Preemptive shard load: when a remote peer starts a prompt, every peer
  # warms its own layer range immediately (parity main.py:201-212).
  def on_opaque_status(request_id: str, status: str) -> None:
    try:
      data = json.loads(status)
      if data.get("type") == "node_status" and data.get("status") == "start_process_prompt":
        base_shard = Shard.from_dict(data.get("base_shard", {}))
        if data.get("node_id") != node.id:
          current = node.get_current_shard(base_shard)
          node._spawn(engine.ensure_shard(current))
    except Exception as e:
      if DEBUG >= 2:
        print(f"preemptive load error: {e!r}")

  node.on_opaque_status.register("main-preemptive-load").on_next(on_opaque_status)

  # Throttled download-progress broadcast at <= 5 Hz (parity main.py:214-224).
  last_broadcast = {"t": 0.0}

  def on_progress(shard, event):
    now = time.monotonic()
    if now - last_broadcast["t"] < 0.2 and not getattr(event, "is_complete", False):
      return
    last_broadcast["t"] = now
    payload = event.to_dict() if hasattr(event, "to_dict") else dict(event)
    node._spawn(node.broadcast_opaque_status("", json.dumps({
      "type": "download_progress", "node_id": node.id, "progress": payload,
    })))

  if downloader is not None:
    downloader.on_progress.register("main-progress").on_next(on_progress)


async def _resolve_cli_tokenizer(model_name: str, engine_classname: str):
  """Tokenizer for the one-shot CLI flows (synthetic/dummy cards never touch
  the network)."""
  if model_name.startswith("synthetic") or model_name == "dummy":
    from xotorch_tpu.inference.tokenizers import DummyTokenizer
    return DummyTokenizer()
  return await resolve_tokenizer(get_repo(model_name, engine_classname))


async def run_model_cli(node: Node, engine_classname: str, model_name: str, prompt: str) -> None:
  """One-shot generate (parity main.py:226-256)."""
  shard = build_base_shard(model_name, engine_classname)
  if shard is None:
    print(f"Error: unsupported model '{model_name}' for engine {engine_classname}")
    return
  tokenizer = await _resolve_cli_tokenizer(model_name, engine_classname)
  if model_name.startswith("synthetic") or model_name == "dummy":
    final_prompt = prompt
  else:
    final_prompt = tokenizer.apply_chat_template(
      [{"role": "user", "content": prompt}], tokenize=False, add_generation_prompt=True
    )
  request_id = str(uuid.uuid4())
  done = asyncio.Event()
  out = {}

  def on_token(req_id, tokens, is_finished):
    if req_id != request_id:
      return
    out["tokens"] = list(tokens)
    if is_finished:
      done.set()

  node.on_token.register("cli-wait-response").on_next(on_token)
  started = time.monotonic()
  await node.process_prompt(shard, final_prompt, request_id)
  try:
    await asyncio.wait_for(done.wait(), timeout=300)
  except asyncio.TimeoutError:
    print("Generation timed out")
    return
  elapsed = time.monotonic() - started
  tokens = out.get("tokens", [])
  eos = getattr(tokenizer, "eos_token_id", None)
  text = tokenizer.decode([t for t in tokens if t != eos])
  print(text)
  print(f"\n[{len(tokens)} tokens in {elapsed:.1f}s = {len(tokens)/max(elapsed,1e-9):.1f} tok/s]", file=sys.stderr)


async def train_model_cli(node: Node, engine_classname: str, model_name: str, args) -> None:
  """Distributed train loop (parity main.py:272-315) — engine leaves exist
  here, unlike the reference."""
  from xotorch_tpu.train.dataset import iterate_batches, load_dataset
  shard = build_base_shard(model_name, engine_classname)
  if shard is None:
    print(f"Error: unsupported model '{model_name}'")
    return
  train_set, valid_set, test_set = load_dataset(args.data)
  tokenizer = await _resolve_cli_tokenizer(model_name, engine_classname)
  if args.resume_checkpoint:
    # Ring-wide: every peer loads its own layer range from the checkpoint
    # directory before the first step (the flag was parsed-but-dead in round
    # 1 — VERDICT weak #5; the reference's engine load_checkpoint was a
    # no-op, inference_engine.py:31-35).
    await node.coordinate_resume(shard, args.resume_checkpoint)
  losses = []
  for it, batch in enumerate(iterate_batches(train_set, tokenizer, args.batch_size, args.sequence_length)):
    if it >= args.iters:
      break
    inputs, targets, lengths = batch
    loss, _ = await node.enqueue_example(shard, inputs, targets, lengths, train=True)
    losses.append(loss)
    print(f"iter {it}: loss={loss:.4f}")
    if args.save_every > 0 and (it + 1) % args.save_every == 0:
      await node.coordinate_save(shard, it + 1, args.save_checkpoint_dir)


async def eval_model_cli(node: Node, engine_classname: str, model_name: str, args) -> None:
  from xotorch_tpu.train.dataset import iterate_batches, load_dataset
  shard = build_base_shard(model_name, engine_classname)
  _, _, test_set = load_dataset(args.data)
  tokenizer = await _resolve_cli_tokenizer(model_name, engine_classname)
  losses = []
  for batch in iterate_batches(test_set, tokenizer, args.batch_size, args.sequence_length):
    inputs, targets, lengths = batch
    loss, _ = await node.enqueue_example(shard, inputs, targets, lengths, train=False)
    losses.append(loss)
  if losses:
    print(f"eval loss: {sum(losses)/len(losses):.4f} over {len(losses)} batches")


async def async_main(args) -> None:
  if args.models_seed_dir:
    # Move pre-seeded checkpoint dirs into XOT_HOME before anything resolves
    # models, so ensure_shard's local-complete fast path and tokenizer
    # resolution find them (parity reference main.py:251-255).
    from xotorch_tpu.download.hf_shard_download import seed_models
    await seed_models(args.models_seed_dir)
  node, engine, engine_classname, api, topology_viz = build_node(args)
  loop = asyncio.get_running_loop()
  def _on_exit_signal(s):
    # Post-mortem spool BEFORE teardown churns state: with
    # XOT_FLIGHT_DUMP_DIR set, the flight ring + frozen snapshots land on
    # disk so a terminated node's evidence survives the process (the soak
    # orchestrator collects these instead of relying on last-good scrapes).
    try:
      node.spool_flight(reason=f"signal:{getattr(s, 'name', s)}")
    except Exception as e:
      if DEBUG >= 1:
        print(f"flight spool on {s} failed: {e!r}")
    spawn_detached(shutdown(s, loop, node.server))

  for sig in (signal.SIGINT, signal.SIGTERM):
    try:
      loop.add_signal_handler(sig, lambda s=sig: _on_exit_signal(s))
    except NotImplementedError:
      pass

  await node.start(wait_for_peers=args.wait_for_peers)
  if topology_viz is not None:
    topology_viz.start()

  if args.chat_tui:
    from xotorch_tpu.viz.chat_tui import run_chat_tui
    model = args.model_name or args.default_model or "llama-3.2-1b"
    tokenizer = await _resolve_cli_tokenizer(model, engine_classname)
    await run_chat_tui(node, engine_classname, model, tokenizer)
    await node.stop()
    return

  if args.command == "run":
    model = args.model_name or args.default_model or "llama-3.2-1b"
    await run_model_cli(node, engine_classname, model, args.prompt)
    await node.stop()
    return
  if args.command == "train":
    model = args.model_name or "synthetic-tiny"
    await train_model_cli(node, engine_classname, model, args)
    await node.stop()
    return
  if args.command == "eval":
    model = args.model_name or "synthetic-tiny"
    await eval_model_cli(node, engine_classname, model, args)
    await node.stop()
    return

  runner = await api.run(port=args.chatgpt_api_port)
  try:
    await asyncio.Event().wait()
  finally:
    await runner.cleanup()
    await node.stop()


def run() -> None:
  # XOT_PLATFORM=cpu|tpu pins the JAX platform even when a site hook
  # pre-registered another backend (env JAX_PLATFORMS can be overridden by
  # such hooks; the config update after import cannot).
  platform = knobs.get_str("XOT_PLATFORM", None)
  if platform:
    import jax
    jax.config.update("jax_platforms", platform)
  args = build_parser().parse_args()
  try:
    asyncio.run(async_main(args))
  except KeyboardInterrupt:
    pass


if __name__ == "__main__":
  run()
