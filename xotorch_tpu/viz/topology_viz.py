"""Topology TUI: live ring visualization with per-partition layer ranges.

Parity: /root/reference/xotorch/viz/topology_viz.py:20-378 — an ASCII ring of
nodes (ellipse layout), per-node capability lines, active-node highlighting,
a cluster bf16-TFLOPS gauge, recent prompt/output panel and per-node download
progress — rendered with rich.Live.
"""
from __future__ import annotations

import math
from collections import OrderedDict
from typing import List, Optional, Tuple

from rich.console import Console, Group
from rich.layout import Layout
from rich.live import Live
from rich.panel import Panel
from rich.table import Table
from rich.text import Text

from xotorch_tpu.topology.partitioning import Partition
from xotorch_tpu.topology.topology import Topology
from xotorch_tpu.utils.helpers import pretty_bytes


class TopologyViz:
  def __init__(self, chatgpt_api_endpoints: Optional[List[str]] = None, web_chat_urls: Optional[List[str]] = None):
    self.chatgpt_api_endpoints = chatgpt_api_endpoints or []
    self.web_chat_urls = web_chat_urls or []
    self.topology = Topology()
    self.partitions: List[Partition] = []
    self.node_id: Optional[str] = None
    # Active model's (id, layer count): set from the request status bus so
    # displayed layer ranges are the REAL partition→layer mapping (round 3
    # hardcoded 32 — wrong for every other depth, VERDICT r3 weak #5).
    self.model_id: Optional[str] = None
    self.model_layers: Optional[int] = None
    self.prompts: "OrderedDict[str, str]" = OrderedDict()
    self.outputs: "OrderedDict[str, str]" = OrderedDict()
    self.node_download_progress = {}
    self.console = Console()
    self.layout = Layout()
    self.layout.split_column(Layout(name="main", ratio=3), Layout(name="chat", ratio=2))
    self.live: Optional[Live] = None

  # ------------------------------------------------------------- updates

  def start(self) -> None:
    if self.live is None:
      self.live = Live(self.layout, console=self.console, refresh_per_second=4, transient=False)
      self.live.start()

  def stop(self) -> None:
    if self.live is not None:
      self.live.stop()
      self.live = None

  def update_visualization(self, topology: Topology, partitions: List[Partition], node_id: Optional[str] = None,
                           node_download_progress=None) -> None:
    self.topology = topology
    self.partitions = partitions
    self.node_id = node_id
    if node_download_progress is not None:
      self.node_download_progress = node_download_progress
    self.refresh()

  def update_model(self, model_id: Optional[str], n_layers: Optional[int]) -> None:
    """Record the model the cluster is actively serving (from the
    start_process_prompt status broadcast) so the ring shows its true layer
    ranges."""
    self.model_id = model_id
    self.model_layers = int(n_layers) if n_layers else None
    self.refresh()

  def update_prompt(self, request_id: str, prompt: str) -> None:
    self.prompts[request_id] = prompt
    while len(self.prompts) > 3:
      self.prompts.popitem(last=False)
    self.refresh()

  def update_prompt_output(self, request_id: str, output: str) -> None:
    self.outputs[request_id] = output
    while len(self.outputs) > 3:
      self.outputs.popitem(last=False)
    self.refresh()

  def refresh(self) -> None:
    if self.live is None:
      return
    self.layout["main"].update(Panel(self._render_ring(), title="xot cluster", border_style="blue"))
    self.layout["chat"].update(Panel(self._render_chat(), title="chat", border_style="magenta"))
    self.live.refresh()

  # ------------------------------------------------------------ renderers

  def _flops_gauge(self) -> Text:
    total_tflops = sum(caps.flops.fp16 for _, caps in self.topology.all_nodes())
    # tanh-scaled "GPU poor/rich" gauge (parity :219-249), recalibrated to TPU
    # scale: 1 v5e chip ~ 197 bf16 TFLOPS.
    frac = math.tanh(total_tflops / 800.0)
    width = 30
    filled = int(frac * width)
    bar = "█" * filled + "░" * (width - filled)
    label = "TPU rich" if frac > 0.5 else "TPU poor"
    return Text.assemble(
      (f"{total_tflops:.0f} bf16 TFLOPS ", "bold"),
      (bar, "green" if frac > 0.5 else "yellow"),
      (f" {label}", "dim"),
    )

  def _render_ring(self) -> Group:
    lines: List[Text] = [self._flops_gauge(), Text("")]
    shard_ranges = {}
    # Ranges render only when a model is actually being served (its real
    # depth arrives via update_model) — never from a made-up layer count.
    if self.partitions and self.model_layers:
      from xotorch_tpu.topology.partitioning import map_partitions_to_shards
      try:
        shards = map_partitions_to_shards(self.partitions, self.model_layers,
                                          self.model_id or "model")
        shard_ranges = {p.node_id: (s.start_layer, s.end_layer) for p, s in zip(self.partitions, shards)}
      except ValueError:
        shard_ranges = {}
    order = [p.node_id for p in self.partitions] or [nid for nid, _ in self.topology.all_nodes()]
    for i, nid in enumerate(order):
      caps = self.topology.get_node(nid)
      if caps is None:
        continue
      is_self = nid == self.node_id
      is_active = nid == self.topology.active_node_id
      arrow = " ─▶ " if i < len(order) - 1 else " ─▶ (ring wraps)"
      marker = "●" if is_active else "○"
      style = "bold green" if is_active else ("bold cyan" if is_self else "white")
      range_txt = ""
      if nid in shard_ranges:
        lo, hi = shard_ranges[nid]
        range_txt = f" layers[{lo}..{hi}]"
      lines.append(Text.assemble(
        (f" {marker} ", style),
        (f"{nid[:12]:<14}", style),
        (f"{caps.chip} {pretty_bytes(caps.memory * 1024 * 1024)}", "dim"),
        (range_txt, "yellow"),
        (arrow, "dim"),
      ))
    if self.node_download_progress:
      lines.append(Text(""))
      for nid, progress in self.node_download_progress.items():
        pct = progress.get("percentage", 0) if isinstance(progress, dict) else 0
        lines.append(Text(f" ↓ {nid[:12]}: {pct:.0f}%", style="dim"))
    for url in self.web_chat_urls:
      lines.append(Text(f"\n web chat: {url}", style="blue underline"))
    for ep in self.chatgpt_api_endpoints:
      lines.append(Text(f" api: {ep}", style="dim"))
    return Group(*lines)

  def _render_chat(self) -> Group:
    rows = []
    for request_id in list(self.prompts.keys())[-3:]:
      rows.append(Text.assemble(("prompt: ", "bold yellow"), (self.prompts[request_id][-200:], "")))
      if request_id in self.outputs:
        rows.append(Text.assemble(("output: ", "bold green"), (self.outputs[request_id][-400:], "")))
      rows.append(Text(""))
    return Group(*rows) if rows else Group(Text("no requests yet", style="dim"))
