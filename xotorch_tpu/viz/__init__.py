from xotorch_tpu.viz.topology_viz import TopologyViz

__all__ = ["TopologyViz"]
