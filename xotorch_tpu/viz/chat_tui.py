"""Chat REPL with live tokens/sec — the framework's own throughput probe.

Parity: /root/reference/xotorch/viz/chat_tui.py:11-165 (tok/s measured at the
sampler via on_token, :121-128). This is the measurement BASELINE.md names as
metric (a).
"""
from __future__ import annotations

import asyncio
import time
import uuid
from typing import List, Optional

from xotorch_tpu.models.registry import build_base_shard


async def run_chat_tui(node, inference_engine_classname: str, model_id: str, tokenizer) -> None:
  shard = build_base_shard(model_id, inference_engine_classname)
  if shard is None:
    print(f"Unsupported model: {model_id}")
    return
  print(f"Chatting with {model_id}. Ctrl-D or 'exit' to quit.")
  history: List[dict] = []
  loop = asyncio.get_running_loop()

  while True:
    try:
      user_input = await loop.run_in_executor(None, lambda: input("\n> "))
    except (EOFError, KeyboardInterrupt):
      break
    if user_input.strip() in ("exit", "quit"):
      break
    if not user_input.strip():
      continue
    history.append({"role": "user", "content": user_input})
    try:
      prompt = tokenizer.apply_chat_template(history, tokenize=False, add_generation_prompt=True)
    except Exception:
      prompt = "\n".join(f"{m['role']}: {m['content']}" for m in history) + "\nassistant:"

    request_id = str(uuid.uuid4())
    done = asyncio.Event()
    state = {"tokens": [], "started": None, "printed": 0}

    def on_token(req_id, tokens, is_finished):
      if req_id != request_id:
        return
      if state["started"] is None:
        state["started"] = time.monotonic()
      state["tokens"] = list(tokens)
      new = tokens[state["printed"]:]
      state["printed"] = len(tokens)
      eos = getattr(tokenizer, "eos_token_id", None)
      text = tokenizer.decode([t for t in new if t != eos])
      print(text, end="", flush=True)
      if is_finished:
        done.set()

    callback = node.on_token.register(f"chat-tui-{request_id}")
    callback.on_next(on_token)
    try:
      await node.process_prompt(shard, prompt, request_id)
      await asyncio.wait_for(done.wait(), timeout=300)
      elapsed = time.monotonic() - (state["started"] or time.monotonic())
      n = len(state["tokens"])
      if elapsed > 0 and n:
        print(f"\n[{n} tokens, {n/elapsed:.1f} tok/s]")
      eos = getattr(tokenizer, "eos_token_id", None)
      content = tokenizer.decode([t for t in state["tokens"] if t != eos])
      history.append({"role": "assistant", "content": content})
    except asyncio.TimeoutError:
      print("\n[timed out]")
    finally:
      node.on_token.deregister(f"chat-tui-{request_id}")
