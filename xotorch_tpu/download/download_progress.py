"""Download progress events (parity: download/download_progress.py:1-66)."""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict


@dataclass
class RepoFileProgressEvent:
  repo_id: str
  file_path: str
  downloaded: int
  total: int
  speed: float  # bytes/sec
  status: str  # not_started | in_progress | complete

  def to_dict(self) -> Dict:
    return {
      "repo_id": self.repo_id, "file_path": self.file_path, "downloaded": self.downloaded,
      "total": self.total, "speed": self.speed, "status": self.status,
    }


@dataclass
class RepoProgressEvent:
  repo_id: str
  completed_files: int
  total_files: int
  downloaded_bytes: int
  total_bytes: int
  speed: float
  status: str
  file_progress: Dict[str, RepoFileProgressEvent] = field(default_factory=dict)

  @property
  def percentage(self) -> float:
    return 100.0 * self.downloaded_bytes / self.total_bytes if self.total_bytes else 0.0

  @property
  def eta_seconds(self) -> float:
    remaining = self.total_bytes - self.downloaded_bytes
    return remaining / self.speed if self.speed > 0 else float("inf")

  @property
  def is_complete(self) -> bool:
    return self.status == "complete"

  def to_dict(self) -> Dict:
    return {
      "repo_id": self.repo_id, "completed_files": self.completed_files, "total_files": self.total_files,
      "downloaded_bytes": self.downloaded_bytes, "total_bytes": self.total_bytes, "speed": self.speed,
      "status": self.status, "percentage": self.percentage,
      "file_progress": {k: v.to_dict() for k, v in self.file_progress.items()},
    }
