"""ShardDownloader ABC + Noop fake.

Parity: /root/reference/xotorch/download/shard_download.py:9-50. Engines ask
the downloader to materialise a shard's weight files locally; the downloader
is layer-aware so each peer fetches only the safetensors files its layer
range needs.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from pathlib import Path
from typing import AsyncIterator, Dict, List, Optional, Tuple

from xotorch_tpu.inference.shard import Shard
from xotorch_tpu.utils.helpers import AsyncCallbackSystem


class ShardDownloader(ABC):
  @abstractmethod
  async def ensure_shard(self, shard: Shard, inference_engine_name: str) -> Path:
    """Make the weight files for `shard` available locally, returning the
    model directory. Must dedupe concurrent calls for the same shard."""
    ...

  @property
  @abstractmethod
  def on_progress(self) -> AsyncCallbackSystem:
    ...

  async def get_shard_download_status(self, inference_engine_name: str) -> AsyncIterator[tuple]:
    if False:
      yield  # pragma: no cover


class LocalShardDownloader(ShardDownloader):
  """Serve model dirs already on disk (offline clusters, tests).

  Resolution order: explicit mapping passed to the constructor, then
  `$XOT_MODEL_DIR/<model_id>` if it exists.
  """

  def __init__(self, mapping: Optional[Dict[str, Path]] = None) -> None:
    self.mapping = {k: Path(v) for k, v in (mapping or {}).items()}
    self._on_progress: AsyncCallbackSystem = AsyncCallbackSystem()

  async def ensure_shard(self, shard: Shard, inference_engine_name: str) -> Path:
    from xotorch_tpu.models.registry import split_adapter
    for mid in (shard.model_id, split_adapter(shard.model_id)[0]):
      if mid in self.mapping:
        return self.mapping[mid]
      from xotorch_tpu.utils import knobs
      root = knobs.get_str("XOT_MODEL_DIR", None)
      if root and (Path(root) / mid).exists():
        return Path(root) / mid
    raise FileNotFoundError(f"No local model dir for {shard.model_id}")

  @property
  def on_progress(self) -> AsyncCallbackSystem:
    return self._on_progress


class NoopShardDownloader(ShardDownloader):
  def __init__(self) -> None:
    self._on_progress: AsyncCallbackSystem = AsyncCallbackSystem()

  async def ensure_shard(self, shard: Shard, inference_engine_name: str) -> Path:
    return Path("/tmp/noop_shard")

  @property
  def on_progress(self) -> AsyncCallbackSystem:
    return self._on_progress

  async def get_shard_download_status(self, inference_engine_name: str) -> AsyncIterator[tuple]:
    if False:
      yield
