"""HF shard downloader: layer-filtered, resumable, hash-verified.

Parity: /root/reference/xotorch/download/new_shard_download.py:24-308 +
hf/hf_helpers.py:14-98 — XOT_HOME dir management, HF tree API listing with
retry+cache, resumable range downloads with etag sha verification, LAYER-
AWARE allow patterns derived from the safetensors index weight map (each
peer fetches only its layer range's files), parallel fetch under a
semaphore, in-flight dedupe + path cache, delete/seed.
"""
from __future__ import annotations

import asyncio
import hashlib
import json
import os
import shutil
import time
from pathlib import Path
from typing import AsyncIterator, Dict, List, Optional, Tuple

import aiohttp

from xotorch_tpu.download.download_progress import RepoFileProgressEvent, RepoProgressEvent
from xotorch_tpu.download.shard_download import ShardDownloader
from xotorch_tpu.inference.shard import Shard
from xotorch_tpu.models.registry import get_model_card, get_repo
from xotorch_tpu.utils import knobs
from xotorch_tpu.utils.helpers import DEBUG, AsyncCallbackSystem, spawn_detached


def xot_home() -> Path:
  return Path(knobs.get_str("XOT_HOME", None) or (Path.home() / ".xot_tpu"))


def models_dir() -> Path:
  return xot_home() / "models"


def hf_endpoint() -> str:
  return os.getenv("HF_ENDPOINT", "https://huggingface.co")


def _auth_headers() -> Dict[str, str]:
  token = os.getenv("HF_TOKEN")
  if not token:
    token_file = Path(os.getenv("HF_HOME", Path.home() / ".cache/huggingface")) / "token"
    if token_file.exists():
      token = token_file.read_text().strip()
  return {"Authorization": f"Bearer {token}"} if token else {}


async def fetch_file_list(session: aiohttp.ClientSession, repo_id: str, revision: str = "main",
                          path: str = "") -> List[Dict]:
  """Recursive HF tree API listing with on-disk cache (parity :72-107)."""
  cache_file = xot_home() / "file_lists" / f"{repo_id.replace('/', '--')}--{revision}.json"
  if cache_file.exists():
    try:
      return json.loads(cache_file.read_text())
    except json.JSONDecodeError:
      pass
  url = f"{hf_endpoint()}/api/models/{repo_id}/tree/{revision}"
  files: List[Dict] = []

  async def walk(subpath: str) -> None:
    async with session.get(f"{url}/{subpath}" if subpath else url, headers=_auth_headers()) as resp:
      resp.raise_for_status()
      for entry in await resp.json():
        if entry["type"] == "file":
          files.append({"path": entry["path"], "size": entry["size"]})
        elif entry["type"] == "directory":
          await walk(entry["path"])

  for attempt in range(3):
    try:
      files.clear()
      await walk(path)
      break
    except Exception:
      if attempt == 2:
        raise
      await asyncio.sleep(1.5 ** attempt)
  cache_file.parent.mkdir(parents=True, exist_ok=True)
  cache_file.write_text(json.dumps(files))
  return files


def get_allow_patterns(weight_map: Dict[str, str], shard: Shard) -> List[str]:
  """Files needed for a layer range (parity hf_helpers.py:74-98): shard
  layers' weight files + always config/tokenizer + first/last extras."""
  import re
  default = ["*.json", "*.py", "tokenizer.model", "*.tiktoken", "*.txt", "*.jinja"]
  shard_files = set()
  for tensor_name, file_name in weight_map.items():
    m = re.search(r"(?:^|\.)layers\.(\d+)\.", tensor_name)
    if m is not None:
      if shard.start_layer <= int(m.group(1)) <= shard.end_layer:
        shard_files.add(file_name)
      continue
    is_embed = "embed" in tensor_name
    is_tail = "lm_head" in tensor_name or re.search(r"(?:^|\.)norm\.weight", tensor_name)
    if is_embed and shard.is_first_layer:
      shard_files.add(file_name)
    elif is_tail and shard.is_last_layer:
      shard_files.add(file_name)
    elif not (is_embed or is_tail):
      if shard.is_first_layer:
        shard_files.add(file_name)
  return default + sorted(shard_files)


def _matches(path: str, patterns: List[str]) -> bool:
  import fnmatch
  return any(fnmatch.fnmatch(path, p) or fnmatch.fnmatch(os.path.basename(path), p) for p in patterns)


class HFShardDownloader(ShardDownloader):
  def __init__(self, max_parallel_downloads: int = 8):
    self.max_parallel_downloads = max_parallel_downloads
    self._on_progress: AsyncCallbackSystem = AsyncCallbackSystem()
    self.active_downloads: Dict[Tuple[str, str], asyncio.Task] = {}
    self.completed: Dict[Tuple[str, str], Path] = {}

  @property
  def on_progress(self) -> AsyncCallbackSystem:
    return self._on_progress

  async def ensure_shard(self, shard: Shard, inference_engine_name: str) -> Path:
    """In-flight dedupe + completed-path cache (parity decorator stack
    Singleton(Cached(New)), :243-285)."""
    key = (shard.model_id, f"{shard.start_layer}-{shard.end_layer}")
    if key in self.completed:
      return self.completed[key]
    if key in self.active_downloads:
      return await asyncio.shield(self.active_downloads[key])
    task = spawn_detached(self._download_shard(shard, inference_engine_name))
    self.active_downloads[key] = task
    try:
      path = await asyncio.shield(task)
      self.completed[key] = path
      return path
    finally:
      self.active_downloads.pop(key, None)

  async def _download_shard(self, shard: Shard, inference_engine_name: str) -> Path:
    repo_id = get_repo(shard.model_id, inference_engine_name)
    if repo_id is None or repo_id in ("synthetic", "dummy"):
      raise ValueError(f"No repo for {shard.model_id} under {inference_engine_name}")
    target_dir = models_dir() / repo_id.replace("/", "--")
    target_dir.mkdir(parents=True, exist_ok=True)

    if self._local_complete(target_dir, shard):
      # Seeded / previously-downloaded checkpoint already holds everything
      # this shard needs: serve it without touching the network, so seeded
      # and air-gapped deployments work. Parity intent:
      # /root/reference/xotorch/download/new_shard_download.py:181-194
      # (local file set checked against the allow-patterns before fetching).
      if DEBUG >= 2:
        print(f"Local checkpoint complete for {shard}; skipping download")
      return target_dir

    timeout = aiohttp.ClientTimeout(total=3600, connect=30)
    async with aiohttp.ClientSession(timeout=timeout) as session:
      file_list = await fetch_file_list(session, repo_id)
      # Layer-aware filtering via the safetensors index (parity :181-194).
      weight_map = await self._weight_map(session, repo_id, target_dir, file_list)
      if weight_map:
        patterns = get_allow_patterns(weight_map, shard)
      else:
        patterns = ["*"]
      wanted = [f for f in file_list if _matches(f["path"], patterns)]
      if not weight_map:
        # No-index repo: record the full intended file set BEFORE fetching.
        # checkpoint_complete requires every listed file, so a kill between
        # files can't pass the offline fast path as "complete".
        write_download_manifest(target_dir, [f["path"] for f in wanted])
      if DEBUG >= 2:
        print(f"Downloading {len(wanted)}/{len(file_list)} files for {shard}")

      semaphore = asyncio.Semaphore(self.max_parallel_downloads)
      progress: Dict[str, RepoFileProgressEvent] = {}
      started = time.monotonic()

      async def fetch(f):
        async with semaphore:
          await self._download_file(session, repo_id, f["path"], f["size"], target_dir, progress, shard, started)

      await asyncio.gather(*(fetch(f) for f in wanted))
    return target_dir

  @staticmethod
  def _local_complete(target_dir: Path, shard: Shard) -> bool:
    return checkpoint_complete(target_dir, shard)

  async def _weight_map(self, session, repo_id: str, target_dir: Path, file_list: List[Dict]) -> Optional[Dict[str, str]]:
    index_name = "model.safetensors.index.json"
    if not any(f["path"] == index_name for f in file_list):
      return None
    index_path = target_dir / index_name
    if not index_path.exists():
      url = f"{hf_endpoint()}/{repo_id}/resolve/main/{index_name}"
      async with session.get(url, headers=_auth_headers()) as resp:  # xotlint: disable=http-client-hygiene (raising IS the contract: ensure_shard propagates download failure and callers log, fall back or retry)
        resp.raise_for_status()
        index_path.write_bytes(await resp.read())
    try:
      return json.loads(index_path.read_text()).get("weight_map", {})
    except json.JSONDecodeError:
      return None

  async def _download_file(self, session, repo_id: str, file_path: str, total: int, target_dir: Path,
                           progress: Dict, shard: Shard, started: float) -> None:
    """Resumable range download with hash verification (parity :109-168)."""
    out_path = target_dir / file_path
    out_path.parent.mkdir(parents=True, exist_ok=True)
    if out_path.exists() and out_path.stat().st_size == total:
      progress[file_path] = RepoFileProgressEvent(repo_id, file_path, total, total, 0, "complete")
      self._emit(repo_id, progress, shard, started, total_files=None)
      return

    partial_path = out_path.with_suffix(out_path.suffix + ".partial")
    downloaded = partial_path.stat().st_size if partial_path.exists() else 0
    url = f"{hf_endpoint()}/{repo_id}/resolve/main/{file_path}"
    headers = {**_auth_headers()}
    if downloaded:
      headers["Range"] = f"bytes={downloaded}-"
    t0 = time.monotonic()
    async with session.get(url, headers=headers) as resp:  # xotlint: disable=http-client-hygiene (raising IS the contract: ensure_shard propagates download failure and callers log, fall back or retry)
      if resp.status == 416:  # already fully downloaded
        pass
      else:
        resp.raise_for_status()
        etag = (resp.headers.get("X-Linked-ETag") or resp.headers.get("ETag") or "").strip('"')
        mode = "ab" if downloaded and resp.status == 206 else "wb"
        if mode == "wb":
          downloaded = 0
        # Page-cache writes of 1 MiB chunks between awaited network reads:
        # the loop never waits on disk in practice.
        with open(partial_path, mode) as f:  # xotlint: disable=async-safety (buffered chunk writes)
          async for chunk in resp.content.iter_chunked(1024 * 1024):
            f.write(chunk)
            downloaded += len(chunk)
            speed = downloaded / max(time.monotonic() - t0, 1e-9)
            progress[file_path] = RepoFileProgressEvent(repo_id, file_path, downloaded, total, speed, "in_progress")
            self._emit(repo_id, progress, shard, started, total_files=None)
        # Hash-verify when the etag is a content hash (parity :141-168).
        if etag and len(etag) in (40, 64) and all(c in "0123456789abcdef" for c in etag.lower()):
          def _verify_hash() -> str:
            # Runs in an executor: hashing a multi-GB checkpoint shard
            # would otherwise block the event loop (and every concurrent
            # download's progress) for seconds.
            algo = hashlib.sha1 if len(etag) == 40 else hashlib.sha256
            h = algo()
            if len(etag) == 40:  # git blob sha1
              h.update(f"blob {partial_path.stat().st_size}\0".encode())
            with open(partial_path, "rb") as f:
              for block in iter(lambda: f.read(1024 * 1024), b""):
                h.update(block)
            return h.hexdigest()
          digest = await asyncio.get_running_loop().run_in_executor(None, _verify_hash)
          if digest != etag:
            partial_path.unlink(missing_ok=True)
            raise ValueError(f"Hash mismatch for {file_path}: {digest} != {etag}")
    if partial_path.exists():
      partial_path.rename(out_path)
    progress[file_path] = RepoFileProgressEvent(repo_id, file_path, total, total, 0, "complete")
    self._emit(repo_id, progress, shard, started, total_files=None)

  def _emit(self, repo_id: str, progress: Dict, shard: Shard, started: float, total_files) -> None:
    files = list(progress.values())
    downloaded = sum(f.downloaded for f in files)
    total = sum(f.total for f in files)
    completed = sum(1 for f in files if f.status == "complete")
    elapsed = max(time.monotonic() - started, 1e-9)
    event = RepoProgressEvent(
      repo_id, completed, len(files), downloaded, total, downloaded / elapsed,
      "complete" if completed == len(files) else "in_progress",
      {f.file_path: f for f in files},
    )
    self._on_progress.trigger_all(shard, event)

  async def get_shard_download_status(self, inference_engine_name: str) -> AsyncIterator[tuple]:
    for (model_id, layers), path in self.completed.items():
      yield (path, RepoProgressEvent(model_id, 1, 1, 0, 0, 0, "complete"))

  async def delete_model(self, model_id: str, inference_engine_name: str) -> bool:
    repo_id = get_repo(model_id, inference_engine_name)
    if repo_id is None:
      return False
    target = models_dir() / repo_id.replace("/", "--")
    if target.exists():
      shutil.rmtree(target)
      self.completed = {k: v for k, v in self.completed.items() if k[0] != model_id}
      return True
    return False


# Completion manifest for NO-INDEX repos: written by the downloader BEFORE
# it starts fetching (listing every file it intends to fetch) so a download
# killed between files can never masquerade as complete — offline, a
# multi-file no-index repo with some files missing is otherwise
# indistinguishable from a complete one (ADVICE r5 #2). Seeded /
# hand-populated dirs have no manifest and keep the old heuristic.
MANIFEST_NAME = ".xot_download_manifest.json"


def write_download_manifest(target_dir: Path, file_paths: List[str]) -> None:
  try:
    (target_dir / MANIFEST_NAME).write_text(json.dumps({"files": sorted(file_paths)}))
  except OSError:
    pass  # best-effort: a read-only dir just keeps the network-verify path


def has_tokenizer_artifact(target_dir: Path) -> bool:
  """A file AutoTokenizer can actually BUILD a tokenizer from.
  tokenizer_config.json alone is not one — treating it as sufficient would
  redirect resolution to a dir that then fails to load (ADVISOR: a
  hash-mismatch-deleted tokenizer.model leaves exactly that state)."""
  return any((target_dir / t).exists()
             for t in ("tokenizer.json", "tokenizer.model", "vocab.json", "spiece.model"))


def _find_index(target_dir: Path) -> Optional[Path]:
  """The safetensors index, top-level or one subdir down (some repos nest
  their weights)."""
  top = target_dir / "model.safetensors.index.json"
  if top.exists():
    return top
  return next(target_dir.glob("*/model.safetensors.index.json"), None)


def checkpoint_complete(target_dir: Path, shard: Optional[Shard] = None) -> bool:
  """THE on-disk completeness rule, shared by the downloader's offline fast
  path (shard-filtered) and the UI's model status (whole repo, shard=None).

  Complete means: config.json, a loadable tokenizer artifact, and full
  weight coverage — with a safetensors index, every file the index names
  (filtered to the shard's allow-patterns when a shard is given); without
  one, every file our download MANIFEST names when one exists (written
  before fetching starts, so a download killed BETWEEN files leaves it
  unsatisfied instead of masquerading as complete — ADVICE r5 #2), else
  (seeded / hand-populated dirs, which have no manifest) at least one
  .safetensors AND no interrupted .partial leftovers."""
  if not (target_dir / "config.json").exists():
    return False
  if not has_tokenizer_artifact(target_dir):
    return False
  index = _find_index(target_dir)
  if index is not None:
    try:
      weight_map = json.loads(index.read_text()).get("weight_map", {})
    except (OSError, json.JSONDecodeError):
      return False
    if not weight_map:
      return False
    files = set(weight_map.values())
    if shard is not None:
      patterns = get_allow_patterns(weight_map, shard)
      files = {f for f in files if _matches(f, patterns)}
    base = index.parent
    return bool(files) and all((base / f).exists() for f in files)
  if any(target_dir.rglob("*.partial")):
    return False
  manifest = target_dir / MANIFEST_NAME
  if manifest.exists():
    try:
      files = json.loads(manifest.read_text()).get("files", [])
    except (OSError, json.JSONDecodeError):
      return False  # unreadable manifest: let the network path re-verify
    return bool(files) and all((target_dir / f).exists() for f in files)
  return any(p.suffix == ".safetensors" for p in target_dir.iterdir() if p.is_file())


def local_model_status(model_id: str, inference_engine_name: str) -> Dict:
  """On-disk download status for one registry model — what tinychat's model
  list renders (downloaded flag, bytes on disk) without any network I/O.
  Parity intent: the reference computes the same per-model status for its
  /initial_models route (xotorch/api/chatgpt_api.py model listing +
  new_shard_download status helpers); here it is a pure disk scan so it
  works in zero-egress deployments too. Synthetic models need no download
  and report downloaded=True with zero bytes."""
  from xotorch_tpu.models.registry import get_repo

  repo_id = get_repo(model_id, inference_engine_name)
  if repo_id is None:
    return {"downloaded": False, "download_percentage": None,
            "total_size": None, "total_downloaded": 0}
  if repo_id in ("synthetic", "dummy"):
    return {"downloaded": True, "download_percentage": 100,
            "total_size": 0, "total_downloaded": 0}
  target = models_dir() / repo_id.replace("/", "--")
  if not target.exists():
    return {"downloaded": False, "download_percentage": None,
            "total_size": None, "total_downloaded": 0, "repo": repo_id}
  total = sum(p.stat().st_size for p in target.rglob("*") if p.is_file())
  # ONE completeness rule with the downloader's offline fast path — a model
  # the UI shows as "local" is exactly one ensure_shard serves offline.
  downloaded = checkpoint_complete(target)
  return {
    "downloaded": downloaded,
    # The true remote total is unknowable offline; report 100 for a
    # complete-looking dir so the UI can label it, None mid-download.
    "download_percentage": 100 if downloaded else None,
    "total_size": total if downloaded else None,
    "total_downloaded": total,
    "repo": repo_id,
  }


async def seed_models(seed_dir: str) -> None:
  """Move pre-seeded model dirs into XOT_HOME (parity :51-70)."""
  source = Path(seed_dir)
  if not source.exists():
    return
  models_dir().mkdir(parents=True, exist_ok=True)
  for entry in source.iterdir():
    if entry.is_dir():
      dest = models_dir() / entry.name
      if not dest.exists():
        shutil.move(str(entry), str(dest))
