from xotorch_tpu.download.shard_download import NoopShardDownloader, ShardDownloader

__all__ = ["ShardDownloader", "NoopShardDownloader"]
