from xotorch_tpu.download.shard_download import LocalShardDownloader, NoopShardDownloader, ShardDownloader

__all__ = ["ShardDownloader", "NoopShardDownloader", "LocalShardDownloader"]
