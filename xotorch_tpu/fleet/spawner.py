"""FleetSpawner: slot-template process management for the elastic fleet.

One spawner per router process. It can start any slot in the template and
stop any slot whose pid it knows — including processes a DIFFERENT router
spawned before dying, because every spawn writes the pid into a sidecar
JSON next to the template (atomic replace, same shared-host discipline as
the actuation lease). Liveness is NOT judged here: the router's poll loop
owns reachability; the spawner only answers "did the process I started
exit" for boot-failure attribution.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import tempfile
import time
from typing import Any, Dict, List, Optional

from xotorch_tpu.utils.helpers import DEBUG


class FleetSpawner:

  def __init__(self, slots: List[Dict[str, Any]], pid_path: Optional[str] = None):
    self.slots = {s["name"]: s for s in slots}
    self.pid_path = pid_path
    self._procs: Dict[str, subprocess.Popen] = {}
    self.spawned_total = 0
    self.spawn_failures_total = 0

  # ------------------------------------------------------------ pid sidecar

  def _read_pids(self) -> Dict[str, int]:
    if not self.pid_path:
      return {}
    try:
      with open(self.pid_path) as f:
        doc = json.load(f)
      return {str(k): int(v) for k, v in doc.items()} if isinstance(doc, dict) else {}
    except (OSError, ValueError):
      return {}

  def _write_pids(self, pids: Dict[str, int]) -> None:
    if not self.pid_path:
      return
    try:
      d = os.path.dirname(self.pid_path) or "."
      os.makedirs(d, exist_ok=True)
      fd, tmp = tempfile.mkstemp(dir=d, prefix=".pids.")
      with os.fdopen(fd, "w") as f:
        f.write(json.dumps(pids))
      os.replace(tmp, self.pid_path)
    except OSError as e:
      if DEBUG >= 1:
        print(f"fleet: pid sidecar write failed: {e!r}")

  def pids(self) -> Dict[str, int]:
    """Union of our live Popen handles over the sidecar: the handover
    surface a new lease holder (and the soak's teardown) reads."""
    out = self._read_pids()
    for name, proc in self._procs.items():
      if proc.poll() is None:
        out[name] = proc.pid
    return out

  # ---------------------------------------------------------------- process

  def spawn(self, name: str) -> Optional[int]:
    """Start one slot. Returns the pid, or None when the template has no
    such slot or the exec itself failed (missing binary, bad log path) —
    a spawn that EXITS later is the boot-timeout's business, not ours."""
    slot = self.slots.get(name)
    if slot is None:
      self.spawn_failures_total += 1
      return None
    env = dict(os.environ)
    env.update({str(k): str(v) for k, v in (slot.get("env") or {}).items()})
    try:
      log_path = slot.get("log")
      logf = open(log_path, "ab") if log_path else subprocess.DEVNULL
      try:
        proc = subprocess.Popen([str(a) for a in slot["argv"]], env=env,
                                stdout=logf, stderr=subprocess.STDOUT,
                                start_new_session=True)
      finally:
        if log_path:
          logf.close()
    except OSError as e:
      self.spawn_failures_total += 1
      if DEBUG >= 0:
        print(f"fleet: spawn of {name} failed: {e!r}")
      return None
    old = self._procs.get(name)
    if old is not None:
      old.poll()  # reap a previous incarnation if it already exited
    self._procs[name] = proc
    self.spawned_total += 1
    pids = self._read_pids()
    pids[name] = proc.pid
    self._write_pids(pids)
    if DEBUG >= 0:
      print(f"fleet: spawned {name} pid {proc.pid}")
    return proc.pid

  def terminate(self, name: str, sig: int = signal.SIGTERM) -> bool:
    """Signal one slot's process — ours via the Popen handle, an inherited
    one (spawned by a dead previous lease holder) via the pid sidecar.
    Returns whether a signal was delivered."""
    proc = self._procs.get(name)
    if proc is not None and proc.poll() is None:
      try:
        proc.send_signal(sig)
        return True
      except OSError:
        pass
    pid = self._read_pids().get(name)
    if pid:
      try:
        os.kill(pid, sig)
        return True
      except OSError:
        pass
    return False

  def reap(self, name: str, timeout_s: float = 5.0) -> None:
    """Wait (bounded) for one of OUR processes to exit after terminate();
    inherited pids have no handle to reap and are left to init."""
    proc = self._procs.get(name)
    if proc is None:
      return
    deadline = time.monotonic() + timeout_s
    while proc.poll() is None and time.monotonic() < deadline:
      time.sleep(0.05)
    if proc.poll() is None:
      try:
        proc.kill()
        proc.wait(timeout=2.0)
      except OSError:
        pass
    pids = self._read_pids()
    if pids.pop(name, None) is not None:
      self._write_pids(pids)

  def exited(self, name: str) -> Optional[int]:
    """Exit code of a slot WE spawned that has exited, else None (alive,
    never ours, or inherited)."""
    proc = self._procs.get(name)
    return None if proc is None else proc.poll()
