"""Elastic replica fleet: the controller half that OWNS the replica set.

Eight PRs of front-door machinery observe and steer a STATIC set of
replicas: a SIGKILLed replica is gone forever, a sustained surge can only
shed 429s, and the router process itself is a single point of failure.
This package closes the loop the metrics already make possible:

- **`FleetLease`** (here): a file-based TTL lease that gates controller
  ACTUATION (spawn/retire/respawn) so N stateless-identical routers can
  all route (rendezvous hashing already guarantees they agree on
  placement) while exactly one acts. No coordination service: the lease
  is a JSON file on the shared host, renewed by atomic replace; a failed
  holder simply stops renewing and the TTL hands actuation over.
- **`FleetSpawner`** (spawner.py): the slot template — every replica the
  fleet may ever run, active or latent, with its argv/env/log — and the
  process management to start and stop them. Pids persist to a sidecar
  file so a NEW lease holder can retire processes a dead holder spawned.
- **`FleetController`** (controller.py): one tick per router poll. Dead
  detection (the unreachable/scrape-failure streak), crash respawn down
  the warm cold-start path (persistent XLA compile cache via
  XOT_COMPILE_CACHE_DIR + PRESERVE-style prefix pre-announce before the
  replica enters rotation), scale-up on sustained admission-queue
  pressure, and scale-down of controller-added spares through the
  existing drain lifecycle so no in-flight request dies.

Following the replica-sharding analysis of arXiv 2004.13336, replicas
share nothing at runtime; the controller only ever touches them through
their public HTTP surface plus POSIX process management.
"""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional


def load_template(path: str) -> List[Dict[str, Any]]:
  """Parse a fleet template file: `{"slots": [{name, url, active, argv,
  env, log}, ...]}`. The slot list is the fleet's whole possible world —
  `active` slots are expected to be running already (spawned by the
  operator or harness); latent ones are what scale-up has to offer.
  Validation is strict: a malformed template must fail at boot, not at
  the first 3 a.m. respawn."""
  with open(path) as f:
    doc = json.load(f)
  slots = doc.get("slots")
  if not isinstance(slots, list) or not slots:
    raise ValueError(f"fleet template {path}: 'slots' must be a non-empty list")
  seen = set()
  for s in slots:
    if not isinstance(s, dict) or not s.get("name") or not s.get("url"):
      raise ValueError(f"fleet template {path}: every slot needs name + url")
    if s["name"] in seen:
      raise ValueError(f"fleet template {path}: duplicate slot {s['name']!r}")
    seen.add(s["name"])
    if not isinstance(s.get("argv"), list) or not s["argv"]:
      raise ValueError(f"fleet template {path}: slot {s['name']!r} needs argv")
  return slots


class FleetLease:
  """TTL'd actuation lease over a shared file. `try_acquire()` is the only
  verb: it acquires when the lease is free or expired, renews when we
  already hold it, and reports False while another holder's lease is
  live. Writes go through temp-file + os.replace (atomic on POSIX) and
  are confirmed by read-back, so of two routers racing an expired lease
  at most one can see its own id in the file. The read-back window still
  admits one overlapping tick under a perfectly symmetric race — the
  actuations behind it are idempotent (a double-spawned slot loses the
  port bind and exits), and the very next renewal resolves ownership.

  `path=None` is solo mode: a single router with no HA peers always holds
  the lease and pays zero file I/O."""

  def __init__(self, path: Optional[str], holder: str, ttl_s: float):
    self.path = path
    self.holder = holder
    self.ttl_s = max(0.5, float(ttl_s))
    self.held = path is None
    self.acquired_total = 0
    self.lost_total = 0

  def _read(self) -> Optional[dict]:
    try:
      with open(self.path) as f:
        doc = json.loads(f.read())
      return doc if isinstance(doc, dict) else None
    except (OSError, json.JSONDecodeError):
      return None

  def _write(self, doc: dict) -> bool:
    try:
      d = os.path.dirname(self.path) or "."
      os.makedirs(d, exist_ok=True)
      fd, tmp = tempfile.mkstemp(dir=d, prefix=".lease.")
      with os.fdopen(fd, "w") as f:
        f.write(json.dumps(doc))
      os.replace(tmp, self.path)
      return True
    except OSError:
      return False

  def peek(self) -> Optional[dict]:
    """The current lease row (holder, expires) without touching it."""
    return None if self.path is None else self._read()

  def try_acquire(self, now: Optional[float] = None) -> bool:
    """One tick of the lease protocol. Returns whether we hold actuation
    AFTER this call; the caller diffs against its previous view to emit
    lease.acquired / lease.lost transitions."""
    if self.path is None:
      return True
    now = time.time() if now is None else now
    was = self.held
    cur = self._read()
    free = (cur is None or cur.get("holder") == self.holder
            or float(cur.get("expires") or 0.0) <= now)
    if free and self._write({"holder": self.holder,
                             "expires": now + self.ttl_s, "at": now}):
      back = self._read()
      self.held = bool(back and back.get("holder") == self.holder)
    else:
      self.held = False
    if self.held and not was:
      self.acquired_total += 1
    elif was and not self.held:
      self.lost_total += 1
    return self.held

  def release(self) -> None:
    """Drop the lease on clean shutdown so a peer takes over NOW instead
    of after a full TTL. Best-effort — a crash skips this by definition."""
    if self.path is None or not self.held:
      return
    cur = self._read()
    if cur and cur.get("holder") == self.holder:
      self._write({"holder": "", "expires": 0.0, "at": time.time()})
    self.held = False

  def status(self) -> dict:
    return {
      "mode": "solo" if self.path is None else "file",
      "path": self.path, "holder_id": self.holder, "held": self.held,
      "ttl_s": self.ttl_s, "lease": self.peek(),
      "acquired_total": self.acquired_total, "lost_total": self.lost_total,
    }
