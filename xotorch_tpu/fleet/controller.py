"""FleetController: one actuation tick per router poll.

The controller rides the router's poll loop (it has no clock of its own)
and consumes only what the poll loop already observed: per-replica
reachability, the unified unreachable/scrape-failure streak, admission
compacts (`queued_hwm`, the trailing high-water mark), and lifecycle
state. Every tick:

1. **Lease**: renew/acquire the actuation lease. A non-holder router
   observes and routes but actuates nothing — and resets its own
   debounce counters so a takeover starts from fresh evidence, not from
   pressure it watched while powerless to act.
2. **Adoption**: a latent slot that is REACHABLE was spawned by a
   previous lease holder — adopt it as a controller-scaled spare so this
   holder can retire it later.
3. **Warm-up completion**: a spawned slot that has come up gets the
   PRESERVE-style prefix pre-announce (the router posts its recent
   prompt prefixes to `/v1/prefetch`, which chains into the PR 18 fabric
   offer path) and only THEN clears `warming` — the replica enters
   rotation with its host tier already filling. A slot that misses its
   boot deadline is a counted respawn failure.
4. **Dead detection + respawn**: a desired-active, ever-reachable slot
   whose down-streak (unreachable OR repeated scrape failure — one
   signal, per the observation-loss-is-liveness-loss rule) reaches
   `XOT_FLEET_DEAD_POLLS` is declared dead, killed for certain, and
   respawned into the warm path. Respawns are exempt from the scale
   cooldown: restoring capacity is never rate-limited.
5. **Scale-up**: when EVERY routable replica's trailing queue high-water
   mark sits at `XOT_FLEET_UP_QUEUE`+ for `XOT_FLEET_UP_POLLS`
   consecutive ticks (spill already balances a lopsided fleet; only
   fleet-wide pressure justifies capacity), spawn the next latent slot.
6. **Scale-down**: only controller-scaled spares, only after
   `XOT_FLEET_IDLE_POLLS` idle ticks, and only through the drain
   discipline — `retiring` removes the slot from rotation, in-flight
   work finishes, and the process is terminated at zero inflight. The
   slot's lifecycle is reset to latent-boot semantics (the process is
   intentionally gone; mourning it as "unreachable" would burn a
   drain/probe cycle on a planned exit).
"""
from __future__ import annotations

import signal
import time
from typing import Any, Dict, List, Optional

from xotorch_tpu.fleet import FleetLease, load_template
from xotorch_tpu.fleet.spawner import FleetSpawner
from xotorch_tpu.utils import knobs
from xotorch_tpu.utils.helpers import DEBUG


class FleetController:

  def __init__(self, router, template_path: str, router_id: str):
    self.router = router
    self.router_id = router_id
    self.template_path = template_path
    slots = load_template(template_path)
    self.slot_names = [s["name"] for s in slots]
    pid_path = template_path + ".pids"
    self.spawner = FleetSpawner(slots, pid_path=pid_path)
    lease_path = knobs.get_str("XOT_FLEET_LEASE_PATH")
    self.lease = FleetLease(lease_path, router_id,
                            knobs.get_float("XOT_FLEET_LEASE_TTL_S"))
    self.min_replicas = max(1, knobs.get_int("XOT_FLEET_MIN"))
    raw_max = knobs.get_int("XOT_FLEET_MAX")
    self.max_replicas = raw_max if raw_max > 0 else len(slots)
    self.up_queue = max(1, knobs.get_int("XOT_FLEET_UP_QUEUE"))
    self.up_polls = max(1, knobs.get_int("XOT_FLEET_UP_POLLS"))
    self.idle_polls = max(1, knobs.get_int("XOT_FLEET_IDLE_POLLS"))
    self.dead_polls = max(1, knobs.get_int("XOT_FLEET_DEAD_POLLS"))
    self.cooldown_s = max(0.0, knobs.get_float("XOT_FLEET_COOLDOWN_S"))
    self.boot_timeout_s = max(1.0, knobs.get_float("XOT_FLEET_BOOT_TIMEOUT_S"))
    self.warm_prefixes = max(0, knobs.get_int("XOT_FLEET_WARM_PREFIXES"))
    # Desired world: which slots SHOULD be running. Seeded from the
    # template's `active` flags; actuation mutates it.
    self.desired: Dict[str, bool] = {s["name"]: bool(s.get("active")) for s in slots}
    self.scaled: set = set()          # controller-added spares (retire-eligible)
    self._warm_deadline: Dict[str, float] = {}   # name -> monotonic boot deadline
    self._idle_ticks: Dict[str, int] = {}
    self._up_ticks = 0
    self._last_scale_mono: Optional[float] = None
    self.spawns_total = 0
    self.respawns_total = 0
    self.respawn_failures_total = 0
    self.deaths_total = 0
    self.scale_ups_total = 0
    self.scale_downs_total = 0
    self.retires_total = 0
    self.adopted_total = 0

  # ------------------------------------------------------------------- tick

  def tick(self, now: float) -> None:
    """One controller pass; `now` is the router's monotonic poll stamp.
    Never raises — the poll loop that hosts us must survive anything."""
    try:
      self._tick(now)
    except Exception as e:
      if DEBUG >= 1:
        print(f"fleet[{self.router_id}]: tick failed: {e!r}")

  def _tick(self, now: float) -> None:
    flight = self.router.flight
    was_held = self.lease.held
    held = self.lease.try_acquire()
    if held and not was_held:
      flight.record("lease.acquired", None, holder=self.router_id,
                    path=self.lease.path)
      if DEBUG >= 0:
        print(f"fleet[{self.router_id}]: lease acquired")
    elif was_held and not held:
      flight.record("lease.lost", None, holder=self.router_id,
                    now_held_by=(self.lease.peek() or {}).get("holder"))
      if DEBUG >= 0:
        print(f"fleet[{self.router_id}]: lease lost")
    if not held:
      # Observe-only: debounces restart from scratch if we later acquire,
      # so a takeover acts on pressure IT confirmed, not inherited counts.
      self._up_ticks = 0
      self._idle_ticks.clear()
      return
    self._adopt(now)
    self._warmups(now)
    self._respawn_dead(now)
    self._scale_up(now)
    self._scale_down(now)

  # ------------------------------------------------------------ tick stages

  def _rep(self, name: str):
    return self.router.replicas.get(name)

  def _adopt(self, now: float) -> None:
    """A reachable slot we believe latent was spawned by a previous lease
    holder: adopt it as desired + controller-scaled so it can be retired
    when pressure subsides."""
    for name in self.slot_names:
      rep = self._rep(name)
      if rep is None or self.desired.get(name) or not rep.reachable:
        continue
      self.desired[name] = True
      self.scaled.add(name)
      self.adopted_total += 1
      if DEBUG >= 0:
        print(f"fleet[{self.router_id}]: adopted running slot {name}")

  def _spawn(self, name: str, respawn: bool, now: float) -> bool:
    """Start one slot into the warm path: `warming` keeps it out of
    rotation until the boot + pre-announce completes."""
    rep = self._rep(name)
    if rep is None:
      return False
    rep.warming = True
    rep.retiring = False
    pid = self.spawner.spawn(name)
    if pid is None:
      rep.warming = False
      if respawn:
        self.respawn_failures_total += 1
      return False
    self.desired[name] = True
    self._warm_deadline[name] = now + self.boot_timeout_s
    rep.down_streak = 0  # the streak now judges the NEW process
    if respawn:
      self.router.flight.record("fleet.respawn", None, slot=name, pid=pid,
                                holder=self.router_id)
      self.respawns_total += 1
    else:
      self.router.flight.record("fleet.spawn", None, slot=name, pid=pid,
                                holder=self.router_id)
      self.spawns_total += 1
    return True

  def _warmups(self, now: float) -> None:
    for name in list(self._warm_deadline):
      rep = self._rep(name)
      if rep is None:
        del self._warm_deadline[name]
        continue
      if rep.reachable:
        # Booted: fire the prefix pre-announce; the router clears
        # `warming` (-> eligible for rotation) once the posts settle.
        del self._warm_deadline[name]
        self.router.spawn_warm_announce(rep, self.warm_prefixes)
      elif now >= self._warm_deadline[name]:
        del self._warm_deadline[name]
        rep.warming = False
        self.respawn_failures_total += 1
        if DEBUG >= 0:
          print(f"fleet[{self.router_id}]: slot {name} missed its "
                f"{self.boot_timeout_s:.0f}s boot deadline")
        if name in self.scaled and not rep.lifecycle.ever_reachable:
          # A scale-up that never came alive: give the slot back. The
          # next sustained surge retries it. Crash respawns stay desired
          # — the dead-detector will try again after a fresh streak.
          self.desired[name] = False
          self.scaled.discard(name)

  def _respawn_dead(self, now: float) -> None:
    for name in self.slot_names:
      rep = self._rep(name)
      if (rep is None or not self.desired.get(name) or rep.retiring
          or name in self._warm_deadline):
        continue
      if not rep.lifecycle.ever_reachable or rep.down_streak < self.dead_polls:
        continue
      self.deaths_total += 1
      self.router.flight.record("fleet.dead", None, slot=name,
                                down_streak=rep.down_streak,
                                scrape_failures=rep.scrape_failures_total)
      if DEBUG >= 0:
        print(f"fleet[{self.router_id}]: slot {name} declared dead "
              f"(streak {rep.down_streak}) — respawning")
      # Kill for certain first: a zombie that still holds the port (alive
      # but unscrapable — the observation-loss case) would beat the
      # respawn to the bind.
      self.spawner.terminate(name, signal.SIGKILL)
      self.spawner.reap(name, timeout_s=2.0)
      self._spawn(name, respawn=True, now=now)

  def _scale_up(self, now: float) -> None:
    routable = self.router.routable()
    hwms = []
    for rep in routable:
      q = rep.queue or {}
      hwms.append(int(q.get("queued_hwm") or q.get("queued") or 0))
    pressed = bool(hwms) and min(hwms) >= self.up_queue
    self._up_ticks = self._up_ticks + 1 if pressed else 0
    if self._up_ticks < self.up_polls:
      return
    if sum(1 for v in self.desired.values() if v) >= self.max_replicas:
      return
    if (self._last_scale_mono is not None
        and now - self._last_scale_mono < self.cooldown_s):
      return
    latent = next((n for n in self.slot_names if not self.desired.get(n)), None)
    if latent is None:
      return
    if self._spawn(latent, respawn=False, now=now):
      self.scaled.add(latent)
      self.scale_ups_total += 1
      self._last_scale_mono = now
      self._up_ticks = 0
      if DEBUG >= 0:
        print(f"fleet[{self.router_id}]: scale-up -> {latent} "
              f"(fleet hwm floor {min(hwms)})")

  def _scale_down(self, now: float) -> None:
    active = sum(1 for v in self.desired.values() if v)
    for name in sorted(self.scaled):
      rep = self._rep(name)
      if rep is None or not self.desired.get(name) or name in self._warm_deadline:
        continue
      if rep.retiring:
        if rep.active_requests <= 0 and int((rep.queue or {}).get("queued") or 0) <= 0:
          self._finish_retire(name, rep)
          active -= 1
        continue
      q = rep.queue or {}
      idle = (rep.reachable and rep.active_requests <= 0
              and int(q.get("queued_hwm") or q.get("queued") or 0) <= 0)
      self._idle_ticks[name] = self._idle_ticks.get(name, 0) + 1 if idle else 0
      if self._idle_ticks[name] < self.idle_polls or active <= self.min_replicas:
        continue
      if (self._last_scale_mono is not None
          and now - self._last_scale_mono < self.cooldown_s):
        continue
      rep.retiring = True
      self._last_scale_mono = now
      self.retires_total += 1
      self.router.flight.record("fleet.retire", None, slot=name,
                                idle_ticks=self._idle_ticks[name])
      if DEBUG >= 0:
        print(f"fleet[{self.router_id}]: retiring idle spare {name}")

  def _finish_retire(self, name: str, rep) -> None:
    """Inflight has drained: stop the process and return the slot to
    latent. Lifecycle resets to boot semantics — a PLANNED exit must not
    register as an unreachable drain."""
    self.spawner.terminate(name, signal.SIGTERM)
    self.spawner.reap(name, timeout_s=10.0)
    self.desired[name] = False
    self.scaled.discard(name)
    self._idle_ticks.pop(name, None)
    rep.retiring = False
    rep.warming = False
    rep.reachable = False
    rep.queue = None
    rep.down_streak = 0
    rep.lifecycle = type(rep.lifecycle)(name)
    self.scale_downs_total += 1
    if DEBUG >= 0:
      print(f"fleet[{self.router_id}]: slot {name} retired (latent again)")

  # ----------------------------------------------------------------- export

  def status(self) -> dict:
    return {
      "router_id": self.router_id,
      "template": self.template_path,
      "lease": self.lease.status(),
      "desired": dict(self.desired),
      "scaled": sorted(self.scaled),
      "warming": sorted(self._warm_deadline),
      "pids": self.spawner.pids(),
      "limits": {"min": self.min_replicas, "max": self.max_replicas,
                 "up_queue": self.up_queue, "up_polls": self.up_polls,
                 "idle_polls": self.idle_polls, "dead_polls": self.dead_polls,
                 "cooldown_s": self.cooldown_s,
                 "boot_timeout_s": self.boot_timeout_s},
      "spawns_total": self.spawns_total,
      "respawns_total": self.respawns_total,
      "respawn_failures_total": self.respawn_failures_total,
      "deaths_total": self.deaths_total,
      "scale_ups_total": self.scale_ups_total,
      "scale_downs_total": self.scale_downs_total,
      "retires_total": self.retires_total,
      "adopted_total": self.adopted_total,
      "spawn_failures_total": self.spawner.spawn_failures_total,
    }
