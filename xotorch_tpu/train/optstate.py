"""Optimizer-state persistence for training resume.

A resumed fine-tune that re-initializes AdamW restarts with zero moments —
the first steps after every restart are effectively un-adapted SGD and the
loss trajectory jumps. The reference could not resume at all (its engine
save_checkpoint was a no-op, inference_engine.py:34-41); here the moments
ride alongside the weight/adapter checkpoint as one flat safetensors file.

Format: leaves of the optax state in tree-flatten order, keyed "opt.{i}".
Restore is SHAPE-CHECKED against a freshly initialized state over the
loaded parameters — a checkpoint from a different optimizer, rank, or
model shape refuses loudly instead of silently mis-applying moments.
"""
from __future__ import annotations

from typing import Any


def save_opt_state(opt_state: Any, path) -> None:
  import jax
  import jax.numpy as jnp
  from safetensors.flax import save_file

  leaves = jax.tree_util.tree_leaves(opt_state)
  tensors = {f"opt.{i}": jnp.asarray(leaf) for i, leaf in enumerate(leaves)}
  save_file(tensors, str(path))


def load_opt_state(template: Any, path) -> Any:
  """Rebuild `template`'s tree with the saved leaves. `template` must be a
  freshly initialized state over the SAME trainable tree (the engine calls
  optimizer.init first) — leaf count and shapes are verified."""
  import jax
  import jax.numpy as jnp
  from safetensors import safe_open

  leaves, treedef = jax.tree_util.tree_flatten(template)
  with safe_open(str(path), framework="np") as f:
    saved = {name: f.get_tensor(name) for name in f.keys()}
  if len(saved) != len(leaves):
    raise ValueError(
      f"optimizer checkpoint {path} has {len(saved)} leaves; the current "
      f"optimizer state has {len(leaves)} — different optimizer or model")
  new_leaves = []
  for i, leaf in enumerate(leaves):
    t = saved.get(f"opt.{i}")
    want = tuple(getattr(leaf, "shape", ()))
    if t is None or tuple(t.shape) != want:
      raise ValueError(
        f"optimizer checkpoint {path}: leaf {i} shape "
        f"{None if t is None else tuple(t.shape)} != expected {want}")
    if jnp.dtype(t.dtype) != jnp.dtype(leaf.dtype):
      # A dtype change (different compute dtype, optimizer config) means a
      # different training setup — refuse rather than silently truncate.
      raise ValueError(
        f"optimizer checkpoint {path}: leaf {i} dtype {t.dtype} != "
        f"expected {jnp.dtype(leaf.dtype)}")
    new_leaves.append(jnp.asarray(t))
  return jax.tree_util.tree_unflatten(treedef, new_leaves)
