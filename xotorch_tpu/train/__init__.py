from xotorch_tpu.train.step import make_eval_step, make_train_step

__all__ = ["make_train_step", "make_eval_step"]
