"""JSONL dataset + batcher for the train/eval CLI.

Parity: /root/reference/xotorch/train/dataset.py:1-80 (itself from
mlx-examples): loads {dir}/train.jsonl, valid.jsonl, test.jsonl with a
"text" field per line; batches are padded token arrays with next-token
targets and true lengths.
"""
from __future__ import annotations

import json
import random
from pathlib import Path
from typing import Iterator, List, Tuple

import numpy as np


class Dataset:
  def __init__(self, path: Path):
    self.entries: List[str] = []
    if path.exists():
      with open(path) as f:
        for line in f:
          line = line.strip()
          if line:
            self.entries.append(json.loads(line).get("text", ""))

  def __len__(self) -> int:
    return len(self.entries)

  def __getitem__(self, idx: int) -> str:
    return self.entries[idx]


def load_dataset(data_dir: str) -> Tuple[Dataset, Dataset, Dataset]:
  base = Path(data_dir)
  names = ("train", "valid", "test")
  train, valid, test = (Dataset(base / f"{n}.jsonl") for n in names)
  if len(train) == 0:
    raise ValueError(f"No training data found in {base} (need train.jsonl with 'text' entries)")
  return train, valid, test


def batch_with_lengths(tokens_batch: List[List[int]], max_seq_len: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
  """Pad to batch max, produce next-token targets and true lengths
  (parity :9-23)."""
  lengths = [min(len(t), max_seq_len) for t in tokens_batch]
  width = max(lengths)
  batch = np.zeros((len(tokens_batch), width), dtype=np.int64)
  for i, tokens in enumerate(tokens_batch):
    batch[i, : lengths[i]] = tokens[: lengths[i]]
  inputs = batch[:, :-1]
  targets = batch[:, 1:]
  return inputs, targets, np.asarray([max(l - 1, 1) for l in lengths], dtype=np.int64)


def iterate_batches(
  dataset: Dataset, tokenizer, batch_size: int, max_seq_len: int, train: bool = True, seed: int = 0
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
  """Shuffled epoch iterator (parity :29-44). Warns on >max_seq_len examples
  (the reference warned at 2048, :55-57)."""
  indices = list(range(len(dataset)))
  if train:
    random.Random(seed).shuffle(indices)
  for i in range(0, len(indices) - batch_size + 1, batch_size):
    chunk = [dataset[j] for j in indices[i: i + batch_size]]
    tokens = [tokenizer.encode(text) for text in chunk]
    for t in tokens:
      if len(t) > max_seq_len:
        print(f"Warning: example of length {len(t)} truncated to {max_seq_len}")
    yield batch_with_lengths(tokens, max_seq_len)
