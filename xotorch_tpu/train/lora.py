"""LoRA adapters for shard transformers, TPU-first.

Fulfills the reference's parameter-efficient-training intent (the `xot train`
CLI defaults to a bundled LoRA dataset, main.py:79 + train/data/lora/, but
the reference's engine train leaf was never implemented — SURVEY §0).

Design: adapter tensors live INSIDE the stacked `params["layers"]` pytree as
`lora_<slot>_a` [L, in, r] / `lora_<slot>_b` [L, r, out], so the existing
`lax.scan` over layers carries them with zero structural change — one XLA
layer body, adapters included, regardless of shard depth. The base weights
stay frozen via `optax.masked` (updates for non-adapter leaves are zeroed at
the optimizer, so Adam never allocates moments for them either — the
optimizer state is ~2x adapter size, not 2x model size).

Init follows the standard recipe: A ~ N(0, 0.02), B = 0, so training starts
at the base model exactly. The contribution is scaled by alpha/r with
alpha = 2r (scale 2.0), the common default.
"""
from __future__ import annotations

import re
from typing import Any, Dict, Iterable, Tuple

import jax
import jax.numpy as jnp

import optax

from xotorch_tpu.models.transformer import LORA_SCALE  # noqa: F401 (single source of truth)

Params = Dict[str, Any]

# Slots eligible for adaptation: attention projections by default (the
# classic LoRA target set); MLP projections opt-in.
ATTN_SLOTS = ("wq", "wk", "wv", "wo")
MLP_SLOTS = ("w_gate", "w_up", "w_down")


def lora_names(slot: str) -> Tuple[str, str]:
  return f"lora_{slot}_a", f"lora_{slot}_b"


def add_lora_params(
  params: Params, rank: int, key: jax.Array,
  targets: Iterable[str] = ATTN_SLOTS, scale_init: float = 0.02,
) -> Params:
  """Return params with stacked LoRA tensors added to the layers pytree for
  every target slot present in this shard. Base tensors are untouched."""
  layers = dict(params["layers"])
  for i, slot in enumerate(targets):
    base = layers.get(slot)
    if base is None:
      continue
    if base.ndim == 4:
      # int4 grouped layout, PACKED uint8 [L, G, gs/2, out] (dense targets
      # only; experts are never a LoRA target): logical in = G * gs =
      # G * 2 * (gs/2) — two nibbles per stored byte.
      L, d_in, d_out = base.shape[0], base.shape[1] * base.shape[2] * 2, base.shape[3]
    else:
      L, d_in, d_out = base.shape[0], base.shape[1], base.shape[2]
    a_name, b_name = lora_names(slot)
    k = jax.random.fold_in(key, i)
    dtype = _adapter_dtype(layers, slot)
    layers[a_name] = (jax.random.normal(k, (L, d_in, rank), jnp.float32) * scale_init).astype(dtype)
    layers[b_name] = jnp.zeros((L, rank, d_out), dtype)
  return {**params, "layers": layers}


def _adapter_dtype(layers: Params, slot: str):
  """Adapters follow the base dtype — except over a quantized base (QLoRA,
  models/quantize.py), where they take the scale's compute dtype: integer
  adapters could neither train nor add a fractional delta."""
  base = layers[slot]
  if jnp.issubdtype(base.dtype, jnp.floating):
    return base.dtype
  scale = layers.get(slot + "_scale")
  if scale is None:
    scale = layers.get(slot + "_gscale")
  return scale.dtype if scale is not None else jnp.bfloat16


def has_lora(params: Params) -> bool:
  return any(k.startswith("lora_") for k in params.get("layers", {}))


def lora_mask(params: Params) -> Params:
  """Boolean pytree: True exactly on adapter leaves (for optax.masked)."""

  def mask_layers(layers: Params) -> Params:
    return {k: k.startswith("lora_") for k in layers}

  return {
    k: (mask_layers(v) if k == "layers" else jax.tree.map(lambda _: False, v))
    for k, v in params.items()
  }


def lora_param_counts(params: Params) -> Tuple[int, int]:
  """(trainable adapter param count, total param count)."""
  total = sum(int(x.size) for x in jax.tree.leaves(params))
  adapter = sum(
    int(v.size) for k, v in params.get("layers", {}).items() if k.startswith("lora_")
  )
  return adapter, total


def masked_optimizer(base: optax.GradientTransformation, params: Params) -> optax.GradientTransformation:
  """Freeze everything but the adapters. NOTE optax.masked alone is a trap:
  it passes masked-OUT updates through unchanged (raw gradients applied at
  scale 1 — instant divergence). multi_transform routes frozen leaves to
  set_to_zero, which also allocates no Adam moments for them.

  Operates over trainable_subtree(params) — the structure grads and
  opt_state use everywhere (train/step.py); over an int8-quantized base
  that is the float leaves only, so the base never even appears in the
  optimizer's label tree."""
  from xotorch_tpu.train.step import trainable_subtree
  fl = trainable_subtree(params)
  labels = jax.tree.map(lambda m: "lora" if m else "frozen", lora_mask(fl))
  return optax.multi_transform({"lora": base, "frozen": optax.set_to_zero()}, labels)


def save_lora_checkpoint(params: Params, shard, out_path) -> None:
  """Adapter-ONLY checkpoint: a LoRA fine-tune of a 70B model saves MBs, not
  the 140 GB base (the reference saved nothing at all — its engine
  save_checkpoint was a no-op, inference_engine.py:34-41). Tensor names are
  absolute-layer-indexed (`lora.layers.{i}.{slot}_{a|b}`) so any peer
  holding that layer range can restore its slice."""
  from pathlib import Path
  from safetensors.flax import save_file

  flat: Dict[str, jnp.ndarray] = {}
  for k, v in params["layers"].items():
    if not k.startswith("lora_"):
      continue
    for idx, i in enumerate(range(shard.start_layer, shard.end_layer + 1)):
      flat[f"lora.layers.{i}.{k[len('lora_'):]}"] = v[idx]
  out_path = Path(out_path)
  out_path.parent.mkdir(parents=True, exist_ok=True)
  save_file(flat, str(out_path))


# `{start}-{end}-{iter}` shard-save stem — THE naming rule for sharded
# adapter/checkpoint saves, shared with the engine's checkpoint code.
SHARD_SAVE_RE = re.compile(r"(\d+)-(\d+)-(\d+)")


def adapter_checkpoint_files(path) -> list:
  """Resolve a registered adapter path to its checkpoint FILE list — the one
  dir-to-files rule the engine's load path and the API's listing validation
  share. A file resolves to itself; a directory resolves to all
  `{start}-{end}-{iter}` shard saves, latest iteration per layer range (the
  set a re-partitioned ring merges adapters from)."""
  from pathlib import Path

  p = Path(path)
  if not p.is_dir():
    return [p]
  best: Dict[str, tuple] = {}
  for f in p.glob("*.safetensors"):
    m = SHARD_SAVE_RE.fullmatch(f.stem)
    if not m:
      continue
    sid, it = f"{m.group(1)}-{m.group(2)}", int(m.group(3))
    if sid not in best or it > best[sid][0]:
      best[sid] = (it, f)
  return [f for _, f in sorted(best.values())]


def validate_adapter_file(path, n_layers: int) -> str | None:
  """Listing/registration-time compatibility check for a registered adapter
  (XOT_ADAPTERS) against a base model's card. Reads only the safetensors
  HEADER (names + shapes), never tensor data, so it is cheap enough for
  /v1/models. Returns an error string, or None when compatible.

  `path` may be a single checkpoint file or a directory of shard saves
  (both registry-documented forms) — directories resolve through the same
  rule the engine's load path uses, and coverage is checked over the UNION
  of the resolved file set. Checks everything knowable without loading the
  base weights: tensor names parse as `lora.layers.{i}.{slot}_{a|b}`, slots
  are from the known target set, every slot covers layers 0..n_layers-1
  with BOTH a and b, and all slots agree on one rank. An adapter trained
  for a different-depth base fails here with a clear message instead of a
  request-time 500 deep in load_lora_checkpoint (ADVICE r4)."""
  from safetensors import safe_open

  known = {f"{s}_{ab}" for s in ATTN_SLOTS + MLP_SLOTS for ab in ("a", "b")}
  files = adapter_checkpoint_files(path)
  if not files:
    return f"no adapter checkpoint files under {path}"
  shapes: Dict[str, tuple] = {}
  try:
    for fp in files:
      with safe_open(str(fp), framework="np") as f:
        for n in f.keys():
          shapes[n] = tuple(f.get_slice(n).get_shape())
  except Exception as e:
    return f"unreadable adapter checkpoint: {e}"
  if not shapes:
    return "adapter checkpoint is empty"
  per_slot: Dict[str, set] = {}
  ranks = set()
  for name, shape in shapes.items():
    parts = name.split(".", 3)
    if len(parts) != 4 or parts[0] != "lora" or parts[1] != "layers" or not parts[2].isdigit():
      return f"not an adapter tensor name: {name!r}"
    slot = parts[3]
    if slot not in known:
      return f"unknown adapter slot {slot!r} (expected one of {sorted(known)})"
    if len(shape) != 2:
      return f"{name}: expected 2-D adapter tensor, got shape {shape}"
    ranks.add(shape[1] if slot.endswith("_a") else shape[0])
    per_slot.setdefault(slot, set()).add(int(parts[2]))
  if len(ranks) != 1:
    return f"inconsistent LoRA rank across tensors: {sorted(ranks)}"
  want = set(range(n_layers))
  for slot, got in per_slot.items():
    if got != want:
      missing = sorted(want - got)
      extra = sorted(got - want)
      detail = (f"missing layers {missing[:4]}{'...' if len(missing) > 4 else ''}" if missing
                else f"covers layers beyond the base's {n_layers} ({extra[:4]}...)")
      return f"slot {slot}: {detail} — adapter was trained for a different base depth"
  for slot in {s.rsplit("_", 1)[0] for s in per_slot}:
    if f"{slot}_a" not in per_slot or f"{slot}_b" not in per_slot:
      return f"slot {slot}: missing its a/b pair"
  return None


def is_lora_checkpoint(path) -> bool:
  """True when every tensor in the safetensors FILE is an adapter tensor.
  Directory-to-file resolution is the caller's job (the engine's
  _checkpoint_file_for owns the shard-aware pick — one rule, one place)."""
  from safetensors import safe_open

  try:
    with safe_open(str(path), framework="np") as f:
      names = list(f.keys())
  except Exception:
    return False
  return bool(names) and all(n.startswith("lora.") for n in names)


def load_lora_checkpoint(params: Params, shard, path) -> Params:
  """Merge adapter-only checkpoint FILE(s) into `params` (restacking this
  shard's layer range). `path` may be one file or a list — the absolute layer
  indexing exists precisely so a RE-PARTITIONED ring can restore: a node now
  serving layers 0-15 merges the 0-7 and 8-15 files saved by a previous
  2-node split. The base tree is untouched; layers the file set does not
  cover raise with the missing range."""
  from safetensors import safe_open

  paths = path if isinstance(path, (list, tuple)) else [path]
  raw: Dict[str, jnp.ndarray] = {}
  for p in paths:
    with safe_open(str(p), framework="np") as f:
      for name in f.keys():
        raw[name] = jnp.asarray(f.get_tensor(name))

  slots = sorted({n.split(".", 3)[3] for n in raw if n.startswith("lora.layers.")})
  layers = dict(params["layers"])
  for slot in slots:  # e.g. "wq_a"
    missing = [i for i in range(shard.start_layer, shard.end_layer + 1)
               if f"lora.layers.{i}.{slot}" not in raw]
    if missing:
      raise KeyError(
        f"adapter checkpoint {path} lacks layers {missing} of slot {slot} "
        f"needed by shard {shard.start_layer}-{shard.end_layer}"
      )
    stacked = jnp.stack([
      raw[f"lora.layers.{i}.{slot}"] for i in range(shard.start_layer, shard.end_layer + 1)
    ])
    base_slot = slot.rsplit("_", 1)[0]
    dtype = _adapter_dtype(layers, base_slot) if base_slot in layers else stacked.dtype
    layers[f"lora_{slot}"] = stacked.astype(dtype)
  return {**params, "layers": layers}
