"""Optax training step for shard transformers.

Completes the training leaf the reference declared but never implemented
(node.py:317,324,333 call engine.train/evaluate; no engine defines them —
SURVEY §0). The step is a pure jitted function: under a mesh with the
parallel/mesh.py shardings, XLA turns the same code into dp gradient
all-reduces + tp partial-sum reductions over ICI.

Loss: next-token sparse cross-entropy with a length mask (the dataset
batcher pads; positions >= length contribute nothing, matching the
reference's mlx-derived dataset semantics, train/dataset.py:9-23).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from xotorch_tpu.models.config import ModelConfig
from xotorch_tpu.models.transformer import forward_shard, init_kv_cache


def split_float(tree: Dict[str, Any]) -> Tuple[Dict[str, Any], Dict[str, Any]]:
  """Partition a nested-dict pytree into (float leaves, non-float leaves).

  QLoRA support: an int8-quantized base (models/quantize.py) is not
  differentiable — jax.grad over the whole tree would reject the integer
  leaves. Training differentiates the float subtree only (LoRA adapters +
  norms + scales) with the int leaves closed over; the frozen-base optimizer
  mask already routes every non-adapter update to zero, so the result is
  identical to full-tree grad on an unquantized model."""
  fl: Dict[str, Any] = {}
  nf: Dict[str, Any] = {}
  for k, v in tree.items():
    if isinstance(v, dict):
      a, b = split_float(v)
      if a:
        fl[k] = a
      if b:
        nf[k] = b
    elif jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating):
      fl[k] = v
    else:
      nf[k] = v
  return fl, nf


def merge_trees(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
  """Inverse of split_float: overlay two disjoint nested dicts."""
  out = dict(a)
  for k, v in b.items():
    out[k] = merge_trees(out[k], v) if k in out and isinstance(v, dict) else v
  return out


def trainable_subtree(params: Dict[str, Any]) -> Dict[str, Any]:
  """The float subtree — what optimizers see. Grads, updates, and opt_state
  all live in THIS structure (identical to `params` for an unquantized
  model), so the frozen int8 base is never copied, zero-filled, or walked by
  the optimizer at all."""
  return split_float(params)[0]


def masked_ce_loss(logits: jnp.ndarray, targets: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
  """logits [B,T,V] fp32, targets [B,T] int32, lengths [B] int32."""
  T = logits.shape[1]
  mask = jnp.arange(T, dtype=jnp.int32)[None, :] < lengths[:, None]
  ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
  return (ce * mask).sum() / jnp.maximum(mask.sum(), 1)


def full_model_loss(params, batch: Dict[str, jnp.ndarray], cfg: ModelConfig, ring_mesh=None) -> jnp.ndarray:
  """Loss when one shard holds the whole model (single-peer training).

  ring_mesh: pass the mesh to train sequence-parallel — attention rotates KV
  chunks over the 'sp' axis (ops/ring_attention.py) instead of gathering the
  full sequence per device."""
  inputs, targets, lengths = batch["inputs"], batch["targets"], batch["lengths"]
  B, T = inputs.shape
  cache = init_kv_cache(cfg, cfg.num_layers, B, T, jnp.float32)
  logits, _ = forward_shard(params, inputs, cache, jnp.int32(0), cfg, True, True, ring_mesh=ring_mesh)
  return masked_ce_loss(logits, targets, lengths)


def make_train_step(
  cfg: ModelConfig,
  optimizer: optax.GradientTransformation,
  loss_fn: Optional[Callable] = None,
  ring_mesh=None,
  opt_sharding_fn: Optional[Callable] = None,
) -> Callable:
  """Returns jitted (params, opt_state, batch) -> (params, opt_state, loss).

  `opt_state` must be built over trainable_subtree(params) — identical to
  `params` for float models; for an int8-quantized base it is the float
  leaves only (adapters/norms/scales), so the optimizer neither stores state
  for nor rewrites the frozen base.

  `opt_sharding_fn` (ZeRO-1, parallel/zero.zero1_constraint): applied to the
  updated optimizer state INSIDE the jit so the moments stay dp-sharded at
  rest — XLA then derives the reduce-scatter/all-gather placement on ICI."""
  loss_fn = loss_fn or partial(full_model_loss, cfg=cfg, ring_mesh=ring_mesh)

  @jax.jit
  def train_step(params, opt_state, batch):
    from xotorch_tpu.models.quantize import is_quantized
    from xotorch_tpu.train.lora import has_lora
    # Pytree STRUCTURE predicates: static under trace, no value branch.
    if is_quantized(params) and not has_lora(params):  # xotlint: disable=retrace-hazard (structure test)
      # Without a frozen-base mask the float scales/norms would train against
      # immutable int8 weights — neither a full fine-tune nor a clean freeze.
      raise ValueError("Training a quantized base requires LoRA adapters "
                       "(add_lora_params + masked_optimizer)")
    fl, nf = split_float(params)
    loss, grads = jax.value_and_grad(lambda f: loss_fn(merge_trees(f, nf), batch))(fl)
    updates, opt_state = optimizer.update(grads, opt_state, fl)
    if opt_sharding_fn is not None:
      opt_state = opt_sharding_fn(opt_state)
    return merge_trees(optax.apply_updates(fl, updates), nf), opt_state, loss

  return train_step


def make_eval_step(cfg: ModelConfig, loss_fn: Optional[Callable] = None, ring_mesh=None) -> Callable:
  loss_fn = loss_fn or partial(full_model_loss, cfg=cfg, ring_mesh=ring_mesh)

  @jax.jit
  def eval_step(params, batch):
    return loss_fn(params, batch)

  return eval_step


def shard_loss_and_grads(
  params, cfg: ModelConfig, x: jnp.ndarray, back_grad_or_targets, lengths, is_first: bool, is_last: bool,
  start_layer: int = 0,
):
  """Pipelined training over the ring (parity with the reference's
  forward-activation / backward-gradient chaining, node.py:299-345 +
  Loss{loss,grads} wire design, node_service.proto:45-48).

  Last shard: returns (loss, grad_wrt_input, param_grads) from targets.
  Other shards: returns (loss_passthrough, grad_wrt_input, param_grads) by
  chaining the downstream shard's input-gradient through this shard's vjp.
  param_grads come back in trainable_subtree(params) structure (== params
  for float models; float leaves only over an int8-quantized base).
  """
  B, T = x.shape[0], x.shape[1]
  cache = init_kv_cache(cfg, params["layers"]["attn_norm"].shape[0], B, T, jnp.float32)

  def fwd(p, xin):
    out, _ = forward_shard(p, xin, cache, jnp.int32(0), cfg, is_first, is_last,
                           start_layer=start_layer)
    return out

  # Token inputs (first shard) are not differentiable; close over x there.
  # Grads flow through the float subtree only (int8-quantized bases are
  # non-differentiable by construction — split_float docstring).
  fl, nf = split_float(params)
  if is_last:
    def loss_of(p_fl, xin):
      return masked_ce_loss(fwd(merge_trees(p_fl, nf), xin), back_grad_or_targets, lengths)
    if is_first:
      loss, float_grads = jax.value_and_grad(lambda p: loss_of(p, x))(fl)
      x_grad = jnp.zeros((B, T, cfg.hidden_size), jnp.float32)
    else:
      loss, (float_grads, x_grad) = jax.value_and_grad(loss_of, argnums=(0, 1))(fl, x)
    return loss, x_grad, float_grads
  if is_first:
    out, vjp_fn = jax.vjp(lambda p: fwd(merge_trees(p, nf), x), fl)
    (float_grads,) = vjp_fn(back_grad_or_targets.astype(out.dtype))
    x_grad = jnp.zeros((B, T, cfg.hidden_size), jnp.float32)
  else:
    out, vjp_fn = jax.vjp(lambda p, xin: fwd(merge_trees(p, nf), xin), fl, x)
    float_grads, x_grad = vjp_fn(back_grad_or_targets.astype(out.dtype))
  return jnp.float32(0.0), x_grad, float_grads
