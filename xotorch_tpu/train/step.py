"""Optax training step for shard transformers.

Completes the training leaf the reference declared but never implemented
(node.py:317,324,333 call engine.train/evaluate; no engine defines them —
SURVEY §0). The step is a pure jitted function: under a mesh with the
parallel/mesh.py shardings, XLA turns the same code into dp gradient
all-reduces + tp partial-sum reductions over ICI.

Loss: next-token sparse cross-entropy with a length mask (the dataset
batcher pads; positions >= length contribute nothing, matching the
reference's mlx-derived dataset semantics, train/dataset.py:9-23).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from xotorch_tpu.models.config import ModelConfig
from xotorch_tpu.models.transformer import forward_shard, init_kv_cache


def masked_ce_loss(logits: jnp.ndarray, targets: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
  """logits [B,T,V] fp32, targets [B,T] int32, lengths [B] int32."""
  T = logits.shape[1]
  mask = jnp.arange(T, dtype=jnp.int32)[None, :] < lengths[:, None]
  ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
  return (ce * mask).sum() / jnp.maximum(mask.sum(), 1)


def full_model_loss(params, batch: Dict[str, jnp.ndarray], cfg: ModelConfig, ring_mesh=None) -> jnp.ndarray:
  """Loss when one shard holds the whole model (single-peer training).

  ring_mesh: pass the mesh to train sequence-parallel — attention rotates KV
  chunks over the 'sp' axis (ops/ring_attention.py) instead of gathering the
  full sequence per device."""
  inputs, targets, lengths = batch["inputs"], batch["targets"], batch["lengths"]
  B, T = inputs.shape
  cache = init_kv_cache(cfg, cfg.num_layers, B, T, jnp.float32)
  logits, _ = forward_shard(params, inputs, cache, jnp.int32(0), cfg, True, True, ring_mesh=ring_mesh)
  return masked_ce_loss(logits, targets, lengths)


def make_train_step(
  cfg: ModelConfig,
  optimizer: optax.GradientTransformation,
  loss_fn: Optional[Callable] = None,
  ring_mesh=None,
) -> Callable:
  """Returns jitted (params, opt_state, batch) -> (params, opt_state, loss)."""
  loss_fn = loss_fn or partial(full_model_loss, cfg=cfg, ring_mesh=ring_mesh)

  @jax.jit
  def train_step(params, opt_state, batch):
    loss, grads = jax.value_and_grad(loss_fn)(params, batch)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss

  return train_step


def make_eval_step(cfg: ModelConfig, loss_fn: Optional[Callable] = None, ring_mesh=None) -> Callable:
  loss_fn = loss_fn or partial(full_model_loss, cfg=cfg, ring_mesh=ring_mesh)

  @jax.jit
  def eval_step(params, batch):
    return loss_fn(params, batch)

  return eval_step


def shard_loss_and_grads(
  params, cfg: ModelConfig, x: jnp.ndarray, back_grad_or_targets, lengths, is_first: bool, is_last: bool
):
  """Pipelined training over the ring (parity with the reference's
  forward-activation / backward-gradient chaining, node.py:299-345 +
  Loss{loss,grads} wire design, node_service.proto:45-48).

  Last shard: returns (loss, grad_wrt_input, param_grads) from targets.
  Other shards: returns (loss_passthrough, grad_wrt_input, param_grads) by
  chaining the downstream shard's input-gradient through this shard's vjp.
  """
  B, T = x.shape[0], x.shape[1]
  cache = init_kv_cache(cfg, params["layers"]["attn_norm"].shape[0], B, T, jnp.float32)

  def fwd(p, xin):
    out, _ = forward_shard(p, xin, cache, jnp.int32(0), cfg, is_first, is_last)
    return out

  # Token inputs (first shard) are not differentiable; close over x there.
  if is_last:
    def loss_of(p, xin):
      return masked_ce_loss(fwd(p, xin), back_grad_or_targets, lengths)
    if is_first:
      loss, param_grads = jax.value_and_grad(lambda p: loss_of(p, x))(params)
      x_grad = jnp.zeros((B, T, cfg.hidden_size), jnp.float32)
    else:
      loss, (param_grads, x_grad) = jax.value_and_grad(loss_of, argnums=(0, 1))(params, x)
    return loss, x_grad, param_grads
  if is_first:
    out, vjp_fn = jax.vjp(lambda p: fwd(p, x), params)
    (param_grads,) = vjp_fn(back_grad_or_targets.astype(out.dtype))
    x_grad = jnp.zeros((B, T, cfg.hidden_size), jnp.float32)
  else:
    out, vjp_fn = jax.vjp(fwd, params, x)
    param_grads, x_grad = vjp_fn(back_grad_or_targets.astype(out.dtype))
  return jnp.float32(0.0), x_grad, param_grads
