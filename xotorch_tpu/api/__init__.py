from xotorch_tpu.api.chatgpt_api import ChatGPTAPI

__all__ = ["ChatGPTAPI"]
