"""OpenAI-compatible HTTP API over a Node.

Parity: /root/reference/xotorch/api/chatgpt_api.py:175-607 — same route
surface (/v1/chat/completions with SSE streaming, /v1/models, /modelpool,
/v1/topology, /v1/download/progress, /healthcheck, /quit, model delete /
download), per-request asyncio token queues fed by node.on_token, gpt-*
aliasing, optional injected system prompt, timeout middleware, permissive
CORS, and the bundled web UI served at /.
"""
from __future__ import annotations

import asyncio
import json
import math
import os
import time
import uuid
from pathlib import Path
from typing import Callable, Dict, List, Optional

from aiohttp import web

from xotorch_tpu.inference.engine import inference_engine_classes
from xotorch_tpu.inference.tokenizers import resolve_tokenizer
from xotorch_tpu.models.registry import build_base_shard, get_model_card, get_repo, model_cards, pretty_name
from xotorch_tpu.orchestration.admission import AdmissionRejected
from xotorch_tpu.utils import knobs
from xotorch_tpu.utils.helpers import DEBUG, spawn_detached

WEB_DIR = Path(__file__).parent.parent / "tinychat"


class _StreamRestart(Exception):
  """Internal control flow: a streaming request died to a restartable ring
  failure BEFORE any byte reached the client — the restart loop in
  handle_post_chat_completions resubmits it under a fresh request id."""

  def __init__(self, error: str):
    super().__init__(error)
    self.error = error


class PromptSession:
  def __init__(self, request_id: str, timestamp: int, prompt: str):
    self.request_id = request_id
    self.timestamp = timestamp
    self.prompt = prompt


def extract_images(messages: List[dict]) -> list:
  """Decode image_url content parts (data: URIs) to uint8 HWC arrays, in
  prompt order. Unlike the reference (which remaps every image to the LAST
  placeholder, chatgpt_api.py:97-128), multi-image prompts keep all images."""
  from xotorch_tpu.models.vision import decode_image_data_uri
  images = []
  for m in messages:
    content = m.get("content", "")
    if not isinstance(content, list):
      continue
    for part in content:
      if isinstance(part, dict) and part.get("type") == "image_url":
        url = (part.get("image_url") or {}).get("url", "")
        images.append(decode_image_data_uri(url))
  return images


def build_prompt(tokenizer, messages: List[dict], tools: Optional[list] = None) -> str:
  """Chat-template prompt build with UTF-8 fallback (parity :131-150).
  image_url parts become <image> placeholders at their position in the
  message (LLaVA convention; the engine splices patch features there)."""
  chat = []
  for m in messages:
    content = m.get("content", "")
    if isinstance(content, list):  # multi-part content: text + image parts
      pieces = []
      for part in content:
        if not isinstance(part, dict):
          continue
        if part.get("type") == "text":
          pieces.append(part.get("text", ""))
        elif part.get("type") == "image_url":
          pieces.append("<image>")
      content = "\n".join(pieces)
    chat.append({"role": m.get("role", "user"), "content": content})
  try:
    kwargs = {"tokenize": False, "add_generation_prompt": True}
    if tools:
      kwargs["tools"] = tools
    return tokenizer.apply_chat_template(chat, **kwargs)
  except Exception:
    return "\n".join(f"{m['role']}: {m['content']}" for m in chat) + "\nassistant:"


class ChatGPTAPI:
  def __init__(
    self,
    node,
    inference_engine_classname: str,
    response_timeout: int = 90,
    on_chat_completion_request: Optional[Callable[[str, dict, str], None]] = None,
    default_model: Optional[str] = None,
    system_prompt: Optional[str] = None,
  ):
    self.node = node
    self.inference_engine_classname = inference_engine_classname
    self.response_timeout = response_timeout
    self.on_chat_completion_request = on_chat_completion_request
    self.default_model = default_model or "llama-3.2-1b"
    self.system_prompt = system_prompt
    self.token_queues: Dict[str, asyncio.Queue] = {}
    self.prev_token_lens: Dict[str, int] = {}

    self.app = web.Application(client_max_size=100 * 1024 * 1024)
    self.app.middlewares.append(self._timeout_middleware)
    self.app.middlewares.append(self._cors_middleware)
    r = self.app.router
    r.add_post("/v1/chat/completions", self.handle_post_chat_completions)
    r.add_post("/chat/completions", self.handle_post_chat_completions)
    r.add_post("/v1/chat/token/encode", self.handle_post_chat_token_encode)
    r.add_post("/chat/token/encode", self.handle_post_chat_token_encode)
    r.add_get("/v1/models", self.handle_get_models)
    r.add_get("/models", self.handle_get_models)
    r.add_get("/modelpool", self.handle_model_support)
    r.add_get("/v1/topology", self.handle_get_topology)
    r.add_get("/topology", self.handle_get_topology)
    r.add_get("/healthcheck", self.handle_healthcheck)
    r.add_get("/v1/download/progress", self.handle_get_download_progress)
    r.add_delete("/models/{model_name}", self.handle_delete_model)
    r.add_delete("/v1/models/{model_name}", self.handle_delete_model)
    r.add_post("/download", self.handle_post_download)
    r.add_post("/v1/download", self.handle_post_download)
    r.add_get("/initial_models", self.handle_get_initial_models)
    r.add_get("/quit", self.handle_quit)
    r.add_post("/quit", self.handle_quit)  # the reference's verb (chatgpt_api.py:218)
    # Endpoint parity with the reference's /v1/image/generations
    # (chatgpt_api.py:214,445): its only diffusion card is commented out
    # (models.py:180-181), so the route is dead there — here it answers
    # honestly instead of 404ing clients ported from the reference.
    r.add_post("/v1/image/generations", self.handle_post_image_generations)
    # Observability: span export + prometheus exposition + device traces
    # (the reference declared both intents but wired neither — SURVEY §0, §5).
    r.add_get("/v1/traces", self.handle_get_traces)
    r.add_get("/metrics", self.handle_get_metrics)
    # Flight-recorder snapshots (frozen on watchdog abort / deadline expiry /
    # peer eviction / OOM recovery) + the cluster-wide metric rollup.
    r.add_get("/v1/debug/flight", self.handle_get_flight)
    r.add_get("/v1/cluster/metrics", self.handle_get_cluster_metrics)
    # SLO burn-rate alerts + gray-failure localization: active/recent alerts
    # with burn rates and degraded-peer scores, cluster-rolled like
    # peer_metrics so one scrape sees every node's firing alerts.
    r.add_get("/v1/alerts", self.handle_get_alerts)
    # Critical-path latency anatomy: skew-corrected per-request stage
    # breakdowns, ring-wide per-stage percentiles, and the "which stage
    # grew" two-window diff (orchestration/anatomy.py).
    r.add_get("/v1/anatomy", self.handle_get_anatomy)
    # Metrics history: the bounded downsampling gauge time-series
    # (orchestration/history.py) — windowed record, "which metric moved"
    # diffs, and the trailing compact the router's peer-median drift
    # comparison polls; cluster-rolled like /v1/alerts.
    r.add_get("/v1/history", self.handle_get_history)
    # Runtime fault-injector control (test/soak only, like /quit): lets the
    # soak orchestrator drive wall-clock drop/delay/kill phases in a child
    # process AFTER spawn — XOT_FAULT_SPEC can only be set at startup.
    r.add_get("/v1/debug/faults", self.handle_get_faults)
    r.add_post("/v1/debug/faults", self.handle_post_faults)
    r.add_delete("/v1/debug/faults", self.handle_delete_faults)
    # Live roofline attribution: analytic ceilings + achieved throughput +
    # per-executable time/bytes, with the ring's peers via the status bus.
    r.add_get("/v1/perf", self.handle_get_perf)
    # Bounded admission surface (XOT_MAX_INFLIGHT): live inflight/queue
    # depth + estimated wait, with every peer's compact via the status bus
    # — what the router places load by instead of guessing.
    r.add_get("/v1/queue", self.handle_get_queue)
    # Anticipatory KV prefetch pre-announce (PRESERVE, arXiv 2501.08192):
    # the router names a queued request's prompt so the host-to-HBM warm
    # prefix restore starts while the request is still in flight to us.
    r.add_post("/v1/prefetch", self.handle_post_prefetch)
    # Fleet-wide KV fabric surface (xotorch_tpu/fabric): content-addressed
    # host-tier entry manifests + packed-entry streaming, sibling match
    # probes, and offer announces (router chaining and spill pre-announce
    # land offers here; a sibling's miss path fetches entries back out).
    r.add_post("/v1/kv/match", self.handle_post_kv_match)
    r.add_post("/v1/kv/offer", self.handle_post_kv_offer)
    r.add_get("/v1/kv/{key}", self.handle_get_kv)
    r.add_post("/v1/trace/device/start", self.handle_device_trace_start)
    r.add_post("/v1/trace/device/stop", self.handle_device_trace_stop)
    r.add_get("/", self.handle_root)
    if WEB_DIR.exists():
      r.add_static("/static", WEB_DIR, name="static")

    # Feed per-request queues from the node's token bus (parity :194-198).
    self.node.on_token.register("chatgpt-api-token-handler").on_next(self._enqueue_tokens)

  def _enqueue_tokens(self, request_id: str, tokens: List[int], is_finished: bool) -> None:
    queue = self.token_queues.get(request_id)
    if queue is not None:
      queue.put_nowait((list(tokens), is_finished))

  # ---------------------------------------------------------- middlewares

  @web.middleware
  async def _timeout_middleware(self, request, handler):
    try:
      return await asyncio.wait_for(handler(request), timeout=self.response_timeout * 10)
    except asyncio.TimeoutError:
      return web.json_response({"detail": "Request timed out"}, status=408)

  @web.middleware
  async def _cors_middleware(self, request, handler):
    if request.method == "OPTIONS":
      response = web.Response()
    else:
      try:
        response = await handler(request)
      except web.HTTPException as e:
        response = e
    response.headers["Access-Control-Allow-Origin"] = "*"
    response.headers["Access-Control-Allow-Methods"] = "*"
    response.headers["Access-Control-Allow-Headers"] = "*"
    return response

  @staticmethod
  def _sse_headers() -> dict:
    """Headers for PREPARED StreamResponses: response.prepare() sends the
    header block immediately, so the CORS middleware's post-handler header
    mutation never reaches the wire — every SSE endpoint must carry the
    permissive-CORS set itself (a cross-origin EventSource fails its CORS
    check otherwise)."""
    return {
      "Content-Type": "text/event-stream", "Cache-Control": "no-cache",
      "Access-Control-Allow-Origin": "*", "Access-Control-Allow-Methods": "*",
      "Access-Control-Allow-Headers": "*",
    }

  # --------------------------------------------------------------- routes

  async def handle_root(self, request):
    index = WEB_DIR / "index.html"
    if index.exists():
      return web.FileResponse(index)
    return web.json_response({"name": "xotorch_tpu", "endpoints": ["/v1/chat/completions", "/v1/models", "/v1/topology"]})

  async def handle_healthcheck(self, request):
    return web.json_response({"status": "ok"})

  async def handle_get_traces(self, request):
    """Finished spans, OTLP-style JSON. ?trace_id= filters one trace;
    ?clear=1 drains the buffer after reading; ?format=chrome re-bases the
    assembled spans onto THIS node's clock (estimated ring offsets) and
    returns Chrome trace-event JSON loadable in Perfetto/chrome://tracing."""
    trace_id = request.query.get("trace_id")
    clear = request.query.get("clear") == "1"
    spans = self.node.tracer.export(trace_id=trace_id, clear=clear)
    if request.query.get("format") == "chrome":
      from xotorch_tpu.orchestration.anatomy import chrome_trace
      offsets = self.node.ring_offsets_view()
      return web.json_response({
        "traceEvents": chrome_trace(spans, offsets),
        "displayTimeUnit": "ms",
        "otherData": {"node_id": self.node.id,
                      # Corrected only when some PEER's offset was solved —
                      # the origin's own zero entry is always present.
                      "skew_corrected": any(nid != self.node.id for nid in offsets)},
      })
    return web.json_response({"spans": spans, "count": len(spans)})

  async def handle_get_anatomy(self, request):
    """Latency anatomy. No params: per-stage contribution percentiles over
    the origin's reservoir of skew-corrected breakdowns, plus the current
    ring clock offsets. `?request_id=` serves one request's full breakdown
    (404 when none was assembled). `?diff=<seconds>` answers "which stage
    grew" between the last window and the one before it."""
    store = self.node.anatomy
    rid = request.query.get("request_id")
    if rid:
      b = store.get(rid)
      if b is None:
        return web.json_response(
          {"detail": f"no anatomy breakdown assembled for request {rid}"}, status=404)
      return web.json_response(b)
    diff = request.query.get("diff")
    if diff is not None:
      try:
        window_s = float(diff)
      except ValueError:
        return web.json_response(
          {"detail": f"diff must be a window in seconds, got {diff!r}"}, status=400)
      return web.json_response({"node_id": self.node.id, **store.diff(window_s)})
    offsets = self.node.ring_offsets_view()
    return web.json_response({
      "node_id": self.node.id,
      "enabled": store.enabled,
      "breakdowns": len(store.recent()),
      "total": store.total,
      "stages": store.percentiles(),
      "offsets": offsets,
      "recent_requests": [b.get("request_id") for b in store.recent(16)],
    })

  async def handle_get_flight(self, request):
    """Flight-recorder postmortems. No params: every frozen snapshot plus
    recorder stats. `?request_id=` serves one snapshot (404 when none was
    frozen for that request). `?live=N` additionally returns the last N
    events of the LIVE ring (N=0 / `live=all` for everything) — the
    pre-anomaly view, for debugging a hang that hasn't aborted yet."""
    fl = self.node.flight
    rid = request.query.get("request_id")
    if rid:
      snap = fl.snapshot(rid)
      if snap is None:
        return web.json_response(
          {"detail": f"no flight snapshot frozen for request {rid}"}, status=404)
      return web.json_response(snap)
    body = {"node_id": self.node.id, **fl.stats(), "snapshots": fl.snapshots()}
    live = request.query.get("live")
    if live is not None:
      try:
        n = 0 if live in ("", "all") else max(0, int(live))
      except ValueError:
        return web.json_response(
          {"detail": f"live must be an integer or 'all', got {live!r}"}, status=400)
      body["events"] = fl.tail(n)
    return web.json_response(body)

  async def handle_get_faults(self, request):
    """Current process-wide fault-injector state (test/soak surface)."""
    from xotorch_tpu.networking import faults
    inj = faults.active()
    if inj is None:
      return web.json_response({"installed": False, "rules": 0, "dead_peers": []})
    return web.json_response({
      "installed": True, "rules": len(inj.rules),
      "dead_peers": sorted(inj.dead_peers),
    })

  async def handle_post_faults(self, request):
    """Install a process-wide fault injector at runtime (replaces any
    previous one). Body: {"rules": [{rpc, peer, nth, action, times,
    delay_s}, ...]} — the XOT_FAULT_SPEC rule shape. The soak orchestrator
    uses this for wall-clock fault phases; production deployments should
    firewall /v1/debug/* exactly like /quit."""
    from xotorch_tpu.networking import faults
    try:
      body = await request.json() if request.can_read_body else {}
    except (json.JSONDecodeError, UnicodeDecodeError):
      return web.json_response(
        {"error": {"type": "invalid_request_error", "message": "body must be JSON"}}, status=400)
    rules = body.get("rules")
    if (not isinstance(rules, list) or not rules
        or not all(isinstance(r, dict) and r.get("action") for r in rules)):
      return web.json_response(
        {"error": {"type": "invalid_request_error",
                   "message": "rules must be a non-empty list of objects with an `action`"}},
        status=400)
    try:
      faults.install(faults.FaultInjector(rules))
    except (KeyError, TypeError, ValueError) as e:
      return web.json_response(
        {"error": {"type": "invalid_request_error", "message": f"bad rule: {e!r}"}}, status=400)
    return web.json_response({"installed": True, "rules": len(rules)})

  async def handle_delete_faults(self, request):
    """Remove the installed injector (ends a fault phase)."""
    from xotorch_tpu.networking import faults
    faults.install(None)
    return web.json_response({"installed": False})

  async def handle_get_cluster_metrics(self, request):
    """Cluster metric rollup: this node's summary plus the latest summary
    each peer broadcast over the status bus — one scrape sees every peer.
    A peer whose last summary is older than 3x the topology cadence is
    marked `stale: true` and EXCLUDED from the ring-wide percentile
    aggregate (a dead node's last-good histogram is history, not signal);
    the per-node row is still served so operators see who went quiet."""
    nodes, aggregate = self.node.cluster_metrics_view()
    return web.json_response({"nodes": nodes, "count": len(nodes),
                              "aggregate": aggregate})

  async def handle_get_alerts(self, request):
    """SLO alert surface: this node's full rule status (burn rates, active
    + recent alerts, the live ring decomposition with degraded-peer
    scores) plus each peer's alert compact off the status bus — ONE call
    answers "is anything firing anywhere, and which peer is to blame".
    Stale peers (3x topology cadence, same rule as /v1/cluster/metrics)
    are marked, and `cluster` merges every node's alerts tagged by node."""
    al = self.node.alerts
    loc = al.localization()  # score the ring once for both views below
    body = {"node_id": self.node.id, **al.status(localization=loc)}
    nodes = {self.node.id: al.compact(localization=loc)}
    for nid, summary in self.node.peer_metrics.items():
      alerts = summary.get("alerts") if isinstance(summary, dict) else None
      if alerts is None:
        continue
      if self.node.peer_metrics_stale(nid):
        alerts = {**alerts, "stale": True}
      nodes[nid] = alerts
    cluster_active, cluster_recent = [], []
    for nid, alerts in nodes.items():
      for row in alerts.get("active") or []:
        cluster_active.append({"node_id": nid, **row})
      for row in alerts.get("recent") or []:
        cluster_recent.append({"node_id": nid, **row})
    body["nodes"] = nodes
    body["cluster"] = {
      "active": cluster_active, "recent": cluster_recent,
      "firing": sum(int(a.get("firing") or 0) for a in nodes.values()),
      "degraded_peers": sorted({p for a in nodes.values()
                                for p in (a.get("degraded_peers") or [])}),
    }
    return web.json_response(body)

  async def handle_get_history(self, request):
    """Metrics history: the node's downsampled gauge time-series.
    `?window=<s>` bounds the record; `?metric=<name>` restricts rows to
    one gauge; `?diff=<s>` answers "which metric moved" between the last
    window and the one before it; `?compact=1` serves just the trailing
    rollup (what the router's drift comparison polls). `cluster` carries
    each ring peer's history compact off the status bus, stale-marked
    like /v1/alerts."""
    hist = self.node.history
    if request.query.get("compact") == "1":
      return web.json_response({
        "node_id": self.node.id, "enabled": hist.enabled,
        "compact": hist.compact() if hist.enabled else None,
      })
    diff = request.query.get("diff")
    if diff is not None:
      try:
        window_s = float(diff)
      except ValueError:
        return web.json_response(
          {"detail": f"diff must be a window in seconds, got {diff!r}"}, status=400)
      return web.json_response({"node_id": self.node.id, **hist.diff(window_s)})
    window = request.query.get("window")
    window_s = None
    if window is not None:
      try:
        window_s = float(window)
      except ValueError:
        return web.json_response(
          {"detail": f"window must be seconds, got {window!r}"}, status=400)
    body = {"node_id": self.node.id,
            **hist.status(window_s=window_s, metric=request.query.get("metric"))}
    cluster = {self.node.id: hist.compact()} if hist.enabled else {}
    for nid, summary in self.node.peer_metrics.items():
      h = summary.get("history") if isinstance(summary, dict) else None
      if not h:
        continue
      if self.node.peer_metrics_stale(nid):
        h = {**h, "stale": True}
      cluster[nid] = h
    body["cluster"] = cluster
    return web.json_response(body)

  async def handle_get_perf(self, request):
    """Live performance-attribution report (engine.perf_report): the loaded
    model's analytic bf16/int8/int4 roofline ceilings, predicted vs actual
    resident weight bytes, achieved EWMA throughput/utilization, per-lane
    dispatch totals, the heaviest executables, and pool + host-tier byte
    flows. `cluster` carries each ring peer's compact perf summary (the
    status-bus rollup PR 6 introduced), so one call shows the whole ring."""
    eng = self.node.inference_engine
    report_fn = getattr(eng, "perf_report", None)
    report = report_fn() if report_fn is not None else None
    if report is None:
      return web.json_response(
        {"detail": "engine exposes no perf attribution "
                   "(XOT_PERF_ATTR=0 or a non-JAX engine)"}, status=404)
    cluster = {}
    for nid, summary in self.node.peer_metrics.items():
      perf = summary.get("perf") if isinstance(summary, dict) else None
      if perf:
        cluster[nid] = perf
    local = getattr(eng, "perf_compact", lambda: None)()
    if local is not None:
      cluster[self.node.id] = local
    return web.json_response({"node_id": self.node.id, **report, "cluster": cluster})

  async def handle_get_queue(self, request):
    """Admission surface: this node's gate state (inflight, queued,
    admitted/queued/rejected totals, estimated wait from the cost-model
    tok/s view) plus each peer's admission compact off the status bus —
    the load signal the router routes by. `enabled: false` with an empty
    cluster when every node runs at the default (gate off)."""
    gate = self.node.admission
    local = gate.compact()
    cluster = {self.node.id: local} if gate.enabled else {}
    for nid, summary in self.node.peer_metrics.items():
      adm = summary.get("admission") if isinstance(summary, dict) else None
      if not adm:
        continue
      if self.node.peer_metrics_stale(nid):
        adm = {**adm, "stale": True}
      cluster[nid] = adm
    return web.json_response({
      "node_id": self.node.id, "enabled": gate.enabled,
      # Ring-visible in-flight work on THIS node: the router's drain
      # completion signal even when the gate itself is disabled.
      "active_requests": len(self.node.outstanding_requests),
      # Disaggregated serving role (XOT_FABRIC_ROLE): the router keeps
      # `prefill` replicas out of its routable set and chains through them.
      "fabric_role": knobs.get_str("XOT_FABRIC_ROLE"),
      "admission": local, "cluster": cluster,
    })

  async def handle_post_prefetch(self, request):
    """Pre-announce a queued request's prompt so the engine's host-to-HBM
    warm-prefix restore (PR 3 tier, PRESERVE discipline) starts before the
    request itself arrives. Body: {model, prompt} or {model, messages[,
    tools]} — messages build the exact chat-template prompt a completion
    would run, so the prefix keys match. Fire-and-forget: 202 means the
    prefetch was scheduled, never that a warm prefix exists."""
    try:
      data = await request.json() if request.can_read_body else {}
    except (json.JSONDecodeError, UnicodeDecodeError):
      return web.json_response(
        {"error": {"type": "invalid_request_error", "message": "body must be JSON"}}, status=400)
    if not isinstance(data, dict):
      return web.json_response(
        {"error": {"type": "invalid_request_error",
                   "message": "body must be a JSON object"}}, status=400)
    model = self._resolve_model(data.get("model"))
    shard = build_base_shard(model, self.inference_engine_classname)
    if shard is None:
      return web.json_response({"detail": f"Invalid model: {model}"}, status=400)
    prompt = data.get("prompt")
    messages = data.get("messages")
    if not prompt and messages:
      if (not isinstance(messages, list)
          or not all(isinstance(m, dict) for m in messages)):
        return web.json_response(
          {"error": {"type": "invalid_request_error",
                     "message": "messages must be a list of objects"}}, status=400)
      prompt, _ = await self._request_prompt(model, shard, messages,
                                             data.get("tools"))
    if not prompt or not isinstance(prompt, str):
      return web.json_response(
        {"error": {"type": "invalid_request_error",
                   "message": "a non-empty `prompt` or `messages` list is required"}},
        status=400)
    spawn_detached(self.node.prefetch_prompt(shard, prompt))
    return web.json_response({"accepted": True, "model": model}, status=202)

  # ---------------------------------------------------------- KV fabric

  def _host_kv_store(self):
    """The engine's host KV tier, or None (non-JAX engine, tier disabled,
    or nothing ever spilled). The fabric serves FROM this store only —
    entries in HBM but never spilled are not yet exportable."""
    return getattr(self.node.inference_engine, "_host_kv", None)

  async def handle_post_kv_match(self, request):
    """Fabric probe: the longest usable resident host-tier prefix for a
    sibling's token ids. Body {shard, toks[, limit]}; a clean miss is
    {"key": null} with HTTP 200 — the prober prefills cold, no error."""
    try:
      data = await request.json() if request.can_read_body else {}
    except (json.JSONDecodeError, UnicodeDecodeError):
      return web.json_response(
        {"error": {"type": "invalid_request_error", "message": "body must be JSON"}}, status=400)
    if (not isinstance(data, dict) or not isinstance(data.get("shard"), str)
        or not isinstance(data.get("toks"), list) or not data["toks"]
        or not all(isinstance(t, int) and not isinstance(t, bool) for t in data["toks"])):
      return web.json_response(
        {"error": {"type": "invalid_request_error",
                   "message": "body must carry `shard` (string) and `toks` (list of ints)"}},
        status=400)
    store = self._host_kv_store()
    if store is None or len(store) == 0:
      return web.json_response({"key": None})
    import numpy as np
    from xotorch_tpu.fabric import server as fabric_server
    toks = np.asarray(data["toks"], dtype=np.int64)
    limit = int(data.get("limit") or max(0, toks.shape[0] - 1))
    resp = await asyncio.get_running_loop().run_in_executor(
      None, fabric_server.match_response, store, data["shard"], toks, limit)
    return web.json_response(resp)

  async def handle_get_kv(self, request):
    """Fabric serve: one content-addressed host-tier entry — its manifest
    (leaf table, covered length, digest), or with `?payload=1` the packed
    wire blob in the canonical contiguous layout. 404 for any unknown key,
    including one evicted between a peer's match and its fetch — the peer
    treats that as a miss and prefills cold."""
    key = request.match_info["key"]
    store = self._host_kv_store()
    if store is None or len(store) == 0:
      return web.json_response({"detail": "no host KV tier resident"}, status=404)
    from xotorch_tpu.fabric import server as fabric_server
    loop = asyncio.get_running_loop()
    if request.query.get("payload"):
      t0 = time.monotonic()
      # Packing is a pure host memcpy but can be tens of MB — off the loop.
      blob = await loop.run_in_executor(None, fabric_server.serve_entry, store, key)
      if blob is None:
        return web.json_response({"detail": f"unknown KV entry {key}"}, status=404)
      self.node.flight.record("fabric.serve", None, key=key[:16], bytes=len(blob),
                              secs=round(time.monotonic() - t0, 4))
      return web.Response(body=blob, content_type="application/octet-stream")
    man = await loop.run_in_executor(None, fabric_server.manifest, store, key)
    if man is None:
      return web.json_response({"detail": f"unknown KV entry {key}"}, status=404)
    return web.json_response(man)

  async def handle_post_kv_offer(self, request):
    """Fabric announce: peer `url` holds a host-tier entry covering
    `tokens` for `model`'s shard. Records the offer in the engine's
    directory and kicks the PRESERVE-style anticipatory pull so the KV is
    importing while the chained request is still in flight to us. 202
    means "recorded" — never "fetched"."""
    try:
      data = await request.json() if request.can_read_body else {}
    except (json.JSONDecodeError, UnicodeDecodeError):
      return web.json_response(
        {"error": {"type": "invalid_request_error", "message": "body must be JSON"}}, status=400)
    if not isinstance(data, dict):
      return web.json_response(
        {"error": {"type": "invalid_request_error",
                   "message": "body must be a JSON object"}}, status=400)
    model = self._resolve_model(data.get("model"))
    shard = build_base_shard(model, self.inference_engine_classname)
    if shard is None:
      return web.json_response({"detail": f"Invalid model: {model}"}, status=400)
    tokens = data.get("tokens")
    url = data.get("url")
    if (not isinstance(tokens, list) or not tokens
        or not all(isinstance(t, int) and not isinstance(t, bool) for t in tokens)
        or not isinstance(url, str) or not url):
      return web.json_response(
        {"error": {"type": "invalid_request_error",
                   "message": "an offer must carry `tokens` (list of ints) and `url`"}},
        status=400)
    eng = self.node.inference_engine
    offer_fn = getattr(eng, "fabric_offer", None)
    if offer_fn is None:
      return web.json_response({"accepted": False,
                                "detail": "engine has no KV fabric"}, status=202)
    cur_shard = self.node.get_current_shard(shard)
    accepted = bool(offer_fn(cur_shard, tokens,
                             int(data.get("length") or len(tokens)),
                             int(data.get("nbytes") or 0), url))
    if accepted:
      spawn_detached(eng.prefetch_fabric_offer(cur_shard, tokens))
    return web.json_response({"accepted": accepted, "model": model}, status=202)

  async def handle_get_metrics(self, request):
    body, content_type = self.node.metrics.exposition_with_content_type()
    # Engine-level serving counters (prefix cache, speculative decoding):
    # appended as plain exposition lines — they live on the engine, not the
    # node registry, and only exist on engines that implement the features.
    eng = self.node.inference_engine
    extra = []
    for attr, name, help_text in (
      ("_prefix_hits", "xot_prefix_cache_hits_total", "Prefill prefix-cache hits"),
      ("_prefix_tokens_saved", "xot_prefix_tokens_saved_total", "Prompt tokens whose prefill was skipped"),
      ("_spec_proposed", "xot_spec_tokens_proposed_total", "Speculative draft tokens proposed"),
      ("_spec_accepted", "xot_spec_tokens_accepted_total", "Speculative draft tokens accepted"),
      ("_grow_copies", "xot_kv_grow_copies_total",
       "Contiguous KV grow-copies (zero under XOT_PAGED_KV decode)"),
      ("_commit_copy_bytes", "xot_kv_commit_copy_bytes_total",
       "Device bytes copied committing contiguous prefill KV into pool pages "
       "(zero under paged-native prefill, XOT_PAGED_PREFILL)"),
      ("_unpage_calls", "xot_kv_unpage_total",
       "Paged-to-contiguous cache gathers (zero under virtual KV addressing "
       "unless XOT_PAGED_SPEC=0 restores the legacy fallback)"),
      ("_defrag_moves", "xot_kv_defrag_moves_total",
       "Pages migrated by idle-slot pool compaction (XOT_KV_DEFRAG)"),
      ("_oom_count", "xot_oom_recoveries_total",
       "HBM-exhaustion recoveries (engine._free_device_memory invocations)"),
      ("_prefix_evictions", "xot_prefix_evictions_total",
       "Prefix-cache entries evicted (LRU bound, pool pressure, OOM recovery)"),
      ("_host_kv_hits", "xot_kv_host_hits_total",
       "Prefix lookups served from the host KV tier (XOT_KV_HOST_BYTES)"),
      ("_host_spill_bytes", "xot_kv_spill_bytes_total",
       "Bytes spilled D2H into the host KV tier by prefix evictions"),
      ("_host_fetch_bytes", "xot_kv_fetch_bytes_total",
       "Bytes restored H2D from the host KV tier on warm-prefix admission"),
      ("_fabric_hits", "xot_kv_fabric_hits_total",
       "Prefix entries imported from sibling replicas over the KV fabric"),
      ("_fabric_misses", "xot_kv_fabric_misses_total",
       "Fabric consults that found no usable sibling entry (cold prefill)"),
      ("_fabric_errors", "xot_kv_fabric_errors_total",
       "Fabric transfers dropped (peer error, torn blob, digest mismatch)"),
      ("_fabric_bytes", "xot_kv_fabric_bytes_total",
       "Host-tier bytes imported over the KV fabric from sibling replicas"),
      ("_jit_first_dispatches", "xot_jit_first_dispatch_total",
       "Device dispatches whose executable identity was first seen (jit cache miss: "
       "pays XLA compilation)"),
      ("_jit_cached_dispatches", "xot_jit_cached_dispatch_total",
       "Device dispatches that hit an already-compiled executable"),
    ):
      val = getattr(eng, attr, None)
      if val is not None:
        extra.append(f"# HELP {name} {help_text}\n# TYPE {name} counter\n{name} {val}\n")
    # Per-source breakdown of host-tier hits (local spill vs fabric import):
    # labeled series under the family declared in the table above, so a
    # dashboard can tell a replica warming itself from one warmed by a peer.
    by_src = getattr(eng, "_host_hits_by_source", None)
    if by_src:
      for src in sorted(by_src):
        extra.append(f'xot_kv_host_hits_total{{source="{src}"}} {by_src[src]}\n')
    # Page-pool occupancy gauges (XOT_PAGED_KV; absent until a pool exists).
    stats_fn = getattr(eng, "page_pool_stats", None)
    stats = stats_fn() if stats_fn is not None else None
    if stats is not None:
      for key, name, help_text in (
        ("pages_in_use", "xot_kv_pool_pages_in_use", "KV pool pages currently referenced"),
        ("free_pages", "xot_kv_pool_free_pages", "KV pool pages on the free list"),
        ("peak_pages_in_use", "xot_kv_pool_peak_pages",
         "High-water mark of concurrently referenced KV pool pages"),
        ("fragmentation", "xot_kv_fragmentation_pages",
         "Free pages stranded below the pool's highest used page id "
         "(the holes an idle defrag pass can close)"),
      ):
        if key in stats:
          extra.append(f"# HELP {name} {help_text}\n# TYPE {name} gauge\n{name} {stats[key]}\n")
    # Host-tier KV occupancy gauges (XOT_KV_HOST_BYTES; absent until a
    # prefix eviction first touches the tier).
    host_fn = getattr(eng, "host_kv_stats", None)
    host = host_fn() if host_fn is not None else None
    if host is not None:
      for key, name, help_text in (
        ("bytes", "xot_kv_host_bytes", "Host-RAM bytes held by spilled prefix KV"),
        ("entries", "xot_kv_host_entries", "Prefix entries resident in the host KV tier"),
      ):
        extra.append(f"# HELP {name} {help_text}\n# TYPE {name} gauge\n{name} {host[key]}\n")
    # Roofline-attribution EWMA gauges (XOT_PERF_ATTR; utilization reads 0
    # off-TPU where no chip peak is known). Fed purely from wall timestamps
    # the batcher already takes — scraping these costs no device syncs.
    perf_fn = getattr(eng, "perf_stats", None)
    perf = perf_fn() if perf_fn is not None else None
    if perf is not None:
      for key, name, help_text in (
        ("decode_tok_s", "xot_decode_tok_s",
         "EWMA decode throughput observed at the engine batcher (tokens/s)"),
        ("prefill_tok_s", "xot_prefill_tok_s",
         "EWMA prefill throughput observed at the engine (tokens/s)"),
        ("hbm_util_pct", "xot_hbm_util_pct",
         "EWMA predicted HBM bandwidth utilization vs the chip peak (0 off-TPU)"),
        ("mfu_pct", "xot_mfu_pct",
         "EWMA model FLOP utilization vs the chip peak (0 off-TPU)"),
      ):
        extra.append(f"# HELP {name} {help_text}\n# TYPE {name} gauge\n{name} {perf[key]}\n")
    # Speculation-efficiency gauge (absent until a draft has been verified):
    # EWMA accepted/proposed over the engine's verify rounds — what benchdiff
    # gates acceptance-adjusted tok/s against.
    spec_fn = getattr(eng, "spec_stats", None)
    spec = spec_fn() if spec_fn is not None else None
    if spec is not None:
      for key, name, help_text in (
        ("accept_rate", "xot_spec_accept_rate",
         "EWMA fraction of drafted tokens accepted by verification"),
      ):
        extra.append(f"# HELP {name} {help_text}\n# TYPE {name} gauge\n{name} {spec[key]}\n")
    # SLO alert gauges (XOT_ALERT, default on): firing count, per-family
    # fast-window burn rates, and per-peer hop send RTT EWMAs — the
    # localization signal, scrapeable without touching /v1/alerts.
    alerts = self.node.alerts if self.node.alerts.enabled else None
    if alerts is not None:
      astats = alerts.gauge_stats()
      for key, name, help_text in (
        ("firing", "xot_alerts_firing", "SLO alert rules currently firing on this node"),
        ("drift_firing", "xot_perf_drift_firing",
         "Chronic perf_drift rules currently firing on this node"),
      ):
        extra.append(f"# HELP {name} {help_text}\n# TYPE {name} gauge\n{name} {astats[key]}\n")
      burn = alerts.burn_gauges()
      if burn:
        extra.append("# HELP xot_slo_burn_rate Fast-window SLO burn rate "
                     "(error-budget multiples) per rule family\n"
                     "# TYPE xot_slo_burn_rate gauge\n")
        for family, value in sorted(burn.items()):
          extra.append(f'xot_slo_burn_rate{{family="{family}"}} {value}\n')
      hops = alerts.peer_hop_gauges()
      if hops:
        extra.append("# HELP xot_peer_hop_seconds EWMA hop send RTT to each "
                     "ring peer (gray-failure localization signal)\n"
                     "# TYPE xot_peer_hop_seconds gauge\n")
        for pid, value in sorted(hops.items()):
          extra.append(f'xot_peer_hop_seconds{{peer="{pid}"}} {value}\n')
    # Latency-anatomy gauges (XOT_ANATOMY, default on): reservoir depth,
    # the mean unattributed share of recent breakdowns (the honesty gauge
    # benchdiff gates on committed soak files), and each peer's estimated
    # clock offset relative to this node.
    anat = getattr(self.node, "anatomy", None)
    if anat is not None and anat.enabled:
      astats = anat.gauge_stats()
      for key, name, help_text in (
        ("breakdowns", "xot_anatomy_breakdowns",
         "Skew-corrected stage breakdowns currently held in the anatomy reservoir"),
        ("unattributed_share", "xot_anatomy_unattributed_share",
         "Mean unattributed fraction of recent latency breakdowns (0 = fully attributed)"),
      ):
        extra.append(f"# HELP {name} {help_text}\n# TYPE {name} gauge\n{name} {astats[key]}\n")
      offsets = self.node.ring_offsets_view()
      rows = {nid: o for nid, o in offsets.items() if nid != self.node.id}
      if rows:
        extra.append("# HELP xot_clock_offset_seconds Estimated clock offset of "
                     "each ring peer relative to this node (latency anatomy)\n"
                     "# TYPE xot_clock_offset_seconds gauge\n")
        for pid, off in sorted(rows.items()):
          extra.append(
            f'xot_clock_offset_seconds{{peer="{pid}"}} '
            f'{round(float(off.get("offset_ns") or 0.0) / 1e9, 6)}\n')
    if extra:
      body = body + "".join(extra).encode()
    # aiohttp's content_type kwarg rejects parameters; set the full
    # exposition header (incl. version=0.0.4) directly.
    return web.Response(body=body, headers={"Content-Type": content_type})

  async def handle_device_trace_start(self, request):
    from xotorch_tpu.orchestration.tracing import start_device_trace
    try:
      body = await request.json() if request.can_read_body else {}
    except (json.JSONDecodeError, UnicodeDecodeError):
      return web.json_response(
        {"error": {"type": "invalid_request_error", "message": "body must be JSON"}}, status=400
      )
    logdir = body.get("logdir", "/tmp/xot_jax_trace")
    started = start_device_trace(logdir)
    return web.json_response({"started": started, "logdir": logdir,
                              "max_s": knobs.get_float("XOT_DEVICE_TRACE_MAX_S")})

  async def handle_device_trace_stop(self, request):
    from xotorch_tpu.orchestration.tracing import stop_device_trace
    return web.json_response({"stopped": stop_device_trace()})

  def _adapter_error(self, path: str, n_layers: int):
    """Cached validate_adapter_file: /v1/models may be polled (tinychat
    refreshes the list), and re-opening every safetensors header per request
    would block the event loop on disk I/O for data that only changes when
    the checkpoint changes. Keyed on the path's (mtime_ns, size) — and, for
    DIRECTORY adapters, on the resolved checkpoint files' own
    (name, mtime_ns, size): rewriting a shard save IN PLACE leaves the
    directory's stat unchanged (ADVICE r5 #1), so the dir stat alone would
    serve a stale verdict until restart. adapter_checkpoint_files is the
    same cheap resolution rule the load path uses."""
    import os as _os
    from pathlib import Path as _Path
    from xotorch_tpu.train.lora import adapter_checkpoint_files, validate_adapter_file
    try:
      st = _os.stat(path)
      sig = (n_layers, st.st_mtime_ns, st.st_size)
      if _Path(path).is_dir():
        files = []
        for f in adapter_checkpoint_files(path):
          try:
            fst = _os.stat(f)
            files.append((f.name, fst.st_mtime_ns, fst.st_size))
          except OSError:
            files.append((f.name, None, None))
        sig = sig + (tuple(files),)
    except OSError:
      sig = (n_layers, None, None)
    cache = getattr(self, "_adapter_validation_cache", None)
    if cache is None:
      cache = self._adapter_validation_cache = {}
    hit = cache.get(path)
    if hit is None or hit[0] != sig:
      cache[path] = hit = (sig, validate_adapter_file(path, n_layers))
    return hit[1]

  async def handle_get_models(self, request):
    models = [
      {"id": model_id, "object": "model", "owned_by": "xotorch", "ready": True}
      for model_id, card in model_cards.items()
      if self.inference_engine_classname in card.get("repo", {})
    ]
    # Multi-LoRA serving: registered adapters (XOT_ADAPTERS) are selectable
    # models in their own right. The registry format does not bind an
    # adapter to a base, so variants are advertised under the server's
    # DEFAULT model (the deployment they were registered for); any
    # compatible base still accepts base@name directly. One shared parser
    # (registry.registered_adapters) keeps this list and the engine's
    # resolution in agreement.
    from xotorch_tpu.models.registry import get_model_card, registered_adapters
    base = self.default_model
    if any(m["id"] == base for m in models):
      n_layers = (get_model_card(base) or {}).get("layers", 0)
      for name, path in registered_adapters().items():
        # Header-only shape/coverage check (ADVICE r4): an adapter trained
        # for a different base is surfaced as ready=False with the reason
        # here, instead of a request-time 500 deep in load_lora_checkpoint.
        err = self._adapter_error(path, n_layers) if n_layers else None
        entry = {"id": f"{base}@{name}", "object": "model", "owned_by": "xotorch",
                 "ready": err is None, "adapter_of": base}
        if err is not None:
          entry["error"] = err
        models.append(entry)
    return web.json_response({"object": "list", "data": models})

  async def handle_model_support(self, request):
    """/modelpool: SSE stream of per-model download status ending with
    [DONE] — the reference's wire shape (chatgpt_api.py:268-283, EventSource
    consumer index.js:92-118). Each event is {model_id: {name, layers,
    downloaded, download_percentage, total_size, total_downloaded}}; status
    comes from the shared on-disk completeness rule, scanned off the event
    loop like /initial_models."""
    from xotorch_tpu.download.hf_shard_download import local_model_status

    cards = [(model_id, get_model_card(model_id) or {})
             for model_id in self.node.get_supported_models_for_cluster()]
    cards = [(m, c) for m, c in cards
             if self.inference_engine_classname in c.get("repo", {})]
    response = web.StreamResponse(status=200, headers=self._sse_headers())
    await response.prepare(request)
    loop = asyncio.get_running_loop()
    for model_id, card in cards:
      status = await loop.run_in_executor(
        None, local_model_status, model_id, self.inference_engine_classname)
      event = {model_id: {"name": pretty_name(model_id), "layers": card.get("layers"),
                          **status}}
      await response.write(f"data: {json.dumps(event)}\n\n".encode())
    await response.write(b"data: [DONE]\n\n")
    await response.write_eof()
    return response

  async def handle_get_initial_models(self, request):
    from xotorch_tpu.download.hf_shard_download import local_model_status

    ids = [model_id for model_id, card in model_cards.items()
           if self.inference_engine_classname in card.get("repo", {})]

    def scan():
      # Pure sync disk I/O — run off the event loop so a large models dir
      # (or slow network storage) can't stall in-flight SSE streams.
      return {mid: local_model_status(mid, self.inference_engine_classname) for mid in ids}

    statuses = await asyncio.get_running_loop().run_in_executor(None, scan)
    data = {}
    for model_id in ids:
      entry = {"name": pretty_name(model_id), "layers": model_cards[model_id].get("layers")}
      entry.update(statuses[model_id])
      data[model_id] = entry
    return web.json_response(data)

  async def handle_get_topology(self, request):
    return web.json_response(self.node.current_topology.to_json())

  async def handle_get_download_progress(self, request):
    progress = {}
    for node_id, p in self.node.node_download_progress.items():
      progress[node_id] = p
    return web.json_response(progress)

  async def handle_delete_model(self, request):
    model_name = request.match_info["model_name"]
    from xotorch_tpu.models.registry import split_adapter
    if split_adapter(model_name)[1] is not None:
      # An adapter id resolves to the BASE repo via get_repo — deleting it
      # would rmtree the base weights every other adapter shares. Adapters
      # are registered via XOT_ADAPTERS, not downloaded; refuse loudly.
      return web.json_response(
        {"detail": f"{model_name} is a LoRA adapter variant; deleting it would "
                   "remove the shared base weights. Unregister it from "
                   "XOT_ADAPTERS instead."}, status=400)
    if self.node.shard_downloader is None:
      return web.json_response({"detail": "No downloader"}, status=400)
    delete = getattr(self.node.shard_downloader, "delete_model", None)
    if delete is None:
      return web.json_response({"detail": "Downloader cannot delete"}, status=400)
    deleted = await delete(model_name, self.inference_engine_classname)
    if deleted:
      return web.json_response({"status": "success", "message": f"Model {model_name} deleted"})
    return web.json_response({"detail": f"Model {model_name} not found"}, status=404)

  async def handle_post_download(self, request):
    data = await request.json()
    model_id = data.get("model")
    card = get_model_card(model_id)
    if not card or self.inference_engine_classname not in card.get("repo", {}):
      return web.json_response({"detail": f"Invalid model: {model_id}"}, status=400)
    if self.node.shard_downloader is None:
      return web.json_response({"detail": "No shard downloader configured on this node"}, status=503)
    shard = build_base_shard(model_id, self.inference_engine_classname)
    spawn_detached(self.node.shard_downloader.ensure_shard(shard, self.inference_engine_classname))
    return web.json_response({"status": "success", "message": f"Download started: {model_id}"})

  async def handle_post_image_generations(self, request):
    """501: no diffusion model family is registered. The reference exposes
    the same route but its lone stable-diffusion card is commented out
    (models.py:180-181), so requests there fail with 'Unsupported model';
    this is the same truth stated up front."""
    return web.json_response(
      {"error": {"type": "invalid_request_error",
                 "message": "image generation is not supported: no diffusion model "
                            "family is registered (text and vision-language models only)"}},
      status=501)

  async def handle_quit(self, request):
    response = web.json_response({"detail": "Quit signal received"})
    await response.prepare(request)
    await response.write_eof()
    import os
    import signal
    os.kill(os.getpid(), signal.SIGINT)
    return response

  # ----------------------------------------------------- chat completions

  def _ratelimit_headers(self, remaining: Optional[int] = None,
                         reset_s: Optional[float] = None) -> dict:
    """OpenAI-style x-ratelimit-* response headers from the admission
    gate's live queue estimate (the ROADMAP front-door follow-up): the
    request budget is the concurrency cap plus the bounded queue,
    remaining is what is left of it right now, and reset is the
    cost-model-backed estimated wait for the present population.
    `remaining`/`reset_s` override the live view (the 429 path reports
    the rejection's own numbers); keeping ONE definition of the budget
    here means the 200 and 429 headers can never disagree. Empty when
    the gate is off — defaults-off adds no headers, so disabled serving
    stays byte-identical on the wire."""
    gate = self.node.admission
    if not gate.enabled:
      return {}
    limit = gate.max_inflight + gate.queue_limit
    if remaining is None:
      c = gate.compact()
      used = int(c["inflight"]) + int(c["queued"])
      remaining = max(0, limit - used)
      reset_s = float(c["est_wait_s"]) if used else 0.0
    return {
      "x-ratelimit-limit-requests": str(limit),
      "x-ratelimit-remaining-requests": str(remaining),
      "x-ratelimit-reset-requests": f"{reset_s:g}s",
    }

  def _resolve_model(self, model: Optional[str]) -> str:
    if not model or model.startswith("gpt-"):  # alias gpt-* (parity :322-323)
      return self.default_model
    return model

  async def _request_prompt(self, model: str, shard, messages: List[dict],
                            tools: Optional[list]):
    """THE prompt a completion for these messages would run: server system
    prompt injected when absent, chat template applied. One copy shared by
    completions, token-encode, and prefetch — the prefetch contract is
    that its prefix keys match a real completion's, so the construction
    must never be able to drift between the three. Returns
    (prompt, tokenizer)."""
    if self.system_prompt and not any(m.get("role") == "system" for m in messages):
      messages = [{"role": "system", "content": self.system_prompt}] + messages
    tokenizer = await self._tokenizer_for(model, shard)
    return build_prompt(tokenizer, messages, tools), tokenizer

  async def handle_post_chat_token_encode(self, request):
    """Tokenize a chat request without running it (parity reference
    chatgpt_api.py:287-306 — same response shape: length, num_tokens,
    encoded_tokens, encoded_prompt)."""
    data = await request.json()
    model = self._resolve_model(data.get("model"))
    shard = build_base_shard(model, self.inference_engine_classname)
    if shard is None:
      return web.json_response({"detail": f"Invalid model: {model}"}, status=400)
    # Mirror the completions path exactly (incl. the injected system prompt)
    # so the reported token count matches what a completion would really run.
    prompt, tokenizer = await self._request_prompt(
      model, shard, data.get("messages", []), data.get("tools"))
    tokens = tokenizer.encode(prompt)
    tokens = tokens.tolist() if hasattr(tokens, "tolist") else list(tokens)
    return web.json_response({
      "length": len(prompt),
      "num_tokens": len(tokens),
      "encoded_tokens": tokens,
      "encoded_prompt": prompt,
    })

  async def handle_post_chat_completions(self, request):
    data = await request.json()
    if DEBUG >= 2:
      print(f"chat completions request: {json.dumps(data)[:500]}")
    stream = bool(data.get("stream", False))
    model = self._resolve_model(data.get("model"))
    messages = data.get("messages", [])
    tools = data.get("tools")

    shard = build_base_shard(model, self.inference_engine_classname)
    if shard is None:
      supported = [m for m, c in model_cards.items() if self.inference_engine_classname in c.get("repo", {})]
      return web.json_response(
        {"detail": f"Invalid model: {model}. Supported: {supported}"}, status=400
      )

    prompt, tokenizer = await self._request_prompt(model, shard, messages, tools)

    # Disaggregated serving: a prefill-role replica runs the prompt, spills
    # the KV to its host tier, and hands back a fabric handle instead of
    # decoding — the router chains the handle to a decode replica. Any
    # export failure falls through to normal serving: disaggregation is an
    # optimization, never a new way for a request to fail.
    if knobs.get_str("XOT_FABRIC_ROLE") == "prefill":
      export_fn = getattr(self.node.inference_engine, "prefill_export", None)
      if export_fn is not None:
        try:
          handle = await export_fn(self.node.get_current_shard(shard), prompt)
        except Exception as e:
          if DEBUG >= 1:
            print(f"fabric prefill export failed (serving normally): {e!r}")
          handle = None
        if handle is not None:
          return web.json_response({"object": "kv.handle", "model": model, **handle})

    request_id = str(uuid.uuid4())
    if self.on_chat_completion_request:
      try:
        self.on_chat_completion_request(request_id, data, prompt)
      except Exception as e:
        if DEBUG >= 1:
          print(f"on_chat_completion_request callback error: {e!r}")

    # OpenAI caps: max_tokens (legacy) / max_completion_tokens (current);
    # an explicit null is treated like an absent key.
    max_tokens = data.get("max_completion_tokens")
    if max_tokens is None:
      max_tokens = data.get("max_tokens")
    if max_tokens is not None:
      if isinstance(max_tokens, bool) or not isinstance(max_tokens, int) or max_tokens < 1:
        return web.json_response(
          {"error": {"type": "invalid_request_error",
                     "message": f"max_tokens must be a positive integer, got {max_tokens!r}"}},
          status=400,
        )
    # OpenAI temperature: per-request sampling temperature; the node default
    # applies when absent/null. Rides the ring to whichever peer samples.
    temperature = data.get("temperature")
    if temperature is not None:
      if isinstance(temperature, bool) or not isinstance(temperature, (int, float)) \
         or not (0 <= temperature <= 2):
        return web.json_response(
          {"error": {"type": "invalid_request_error",
                     "message": f"temperature must be a number in [0, 2], got {temperature!r}"}},
          status=400,
        )
      temperature = float(temperature)
    # OpenAI top_p (nucleus sampling): 1 (the OpenAI default) disables it.
    # Values snap to a 0.05 grid: top_p is a compile-time constant of the
    # sampling executable, and an unbounded value set would compile one
    # program per distinct client value.
    top_p = data.get("top_p")
    if top_p is not None:
      if isinstance(top_p, bool) or not isinstance(top_p, (int, float)) or not (0 < top_p <= 1):
        return web.json_response(
          {"error": {"type": "invalid_request_error",
                     "message": f"top_p must be a number in (0, 1], got {top_p!r}"}},
          status=400,
        )
      # Clamp the snap floor to 0.05: a tiny top_p must stay maximally
      # restrictive — snapping to 0.0 would read as "nucleus OFF", the
      # semantic opposite of what the client asked for.
      top_p = max(0.05, round(float(top_p) * 20) / 20)
      if top_p >= 1.0:
        top_p = None  # the OpenAI default: nucleus filtering off
    # OpenAI stop sequences: up to 4 strings; the completion is cut BEFORE
    # the first occurrence and generation is cancelled server-side.
    stop = data.get("stop")
    if stop is not None:
      if isinstance(stop, str):
        stop = [stop]
      if (not isinstance(stop, list) or not stop or len(stop) > 4
          or not all(isinstance(s, str) and s for s in stop)):
        return web.json_response(
          {"error": {"type": "invalid_request_error",
                     "message": f"stop must be a non-empty string or list of 1-4 strings, got {stop!r}"}},
          status=400,
        )
    # OpenAI sampling extras, applied ON DEVICE by the sampler
    # (ops/sampling.py); the reference parsed equivalents and dropped them.
    sampling: dict = {}
    seed = data.get("seed")
    if seed is not None:
      # int64 bound: jax.random.PRNGKey overflows past it — reject here as a
      # 400 rather than surfacing an engine-side 500.
      if isinstance(seed, bool) or not isinstance(seed, int) or not -(2**63) <= seed < 2**63:
        return web.json_response(
          {"error": {"type": "invalid_request_error",
                     "message": f"seed must be a 64-bit integer, got {seed!r}"}}, status=400)
      sampling["seed"] = seed
    min_p = data.get("min_p")
    if min_p is not None:
      # min-p sampling (vLLM/llama.cpp extension; arXiv 2407.01082): a
      # probability floor relative to the max-prob token.
      if isinstance(min_p, bool) or not isinstance(min_p, (int, float)) or not (0 <= min_p <= 1):
        return web.json_response(
          {"error": {"type": "invalid_request_error",
                     "message": f"min_p must be a number in [0, 1], got {min_p!r}"}},
          status=400)
      if min_p:
        sampling["min_p"] = float(min_p)
    for pen_key in ("presence_penalty", "frequency_penalty"):
      pen = data.get(pen_key)
      if pen is not None:
        if isinstance(pen, bool) or not isinstance(pen, (int, float)) or not (-2 <= pen <= 2):
          return web.json_response(
            {"error": {"type": "invalid_request_error",
                       "message": f"{pen_key} must be a number in [-2, 2], got {pen!r}"}},
            status=400)
        if pen:
          sampling[pen_key] = float(pen)
    logit_bias = data.get("logit_bias")
    if logit_bias is not None:
      # isascii() because isdigit() alone admits non-ASCII digit strings
      # (e.g. superscripts) that int() rejects — those must 400 here, not
      # 500 in the engine executor.
      ok = (isinstance(logit_bias, dict) and len(logit_bias) <= 300
            and all(isinstance(k, (str, int)) and str(k).isascii() and str(k).isdigit()
                    and isinstance(v, (int, float)) and not isinstance(v, bool)
                    and -100 <= v <= 100
                    for k, v in logit_bias.items()))
      if not ok:
        return web.json_response(
          {"error": {"type": "invalid_request_error",
                     "message": "logit_bias must map up to 300 non-negative token ids "
                                "to numbers in [-100, 100]"}},
          status=400)
      if logit_bias:
        sampling["logit_bias"] = {str(k): float(v) for k, v in logit_bias.items()}
    # OpenAI logprobs: per-token logprob of the sampled token, plus up to
    # `top_logprobs` (0..20) alternatives — computed ON DEVICE alongside
    # sampling (ops/sampling.sample_logits_logprobs), so the full [B, V]
    # logits still never cross to the host.
    want_logprobs = data.get("logprobs")
    top_logprobs = data.get("top_logprobs")
    if want_logprobs is not None and not isinstance(want_logprobs, bool):
      return web.json_response(
        {"error": {"type": "invalid_request_error",
                   "message": f"logprobs must be a boolean, got {want_logprobs!r}"}}, status=400)
    if top_logprobs is not None:
      if (isinstance(top_logprobs, bool) or not isinstance(top_logprobs, int)
          or not 0 <= top_logprobs <= 20):
        return web.json_response(
          {"error": {"type": "invalid_request_error",
                     "message": f"top_logprobs must be an integer in [0, 20], got {top_logprobs!r}"}},
          status=400)
      if not want_logprobs:
        return web.json_response(
          {"error": {"type": "invalid_request_error",
                     "message": "top_logprobs requires logprobs to be true"}}, status=400)
    if want_logprobs:
      sampling["logprobs"] = int(top_logprobs or 0)
    try:
      images = extract_images(data.get("messages", [])) or None
    except ValueError as e:
      return web.json_response(
        {"error": {"type": "invalid_request_error", "message": str(e)}}, status=400
      )
    if images and not (get_model_card(model) or {}).get("vision"):
      return web.json_response(
        {"error": {"type": "invalid_request_error",
                   "message": f"model {model} does not support image input"}},
        status=400,
      )
    # OpenAI n: independent completions of the same prompt. They compose
    # with the serving stack for free — completions 2..n prefill via the
    # prefix cache and their decodes coalesce in the continuous batcher.
    n = data.get("n")
    if n is None:
      n = 1  # explicit null means "default", like the OpenAI API
    if isinstance(n, bool) or not isinstance(n, int) or not (1 <= n <= 8):
      return web.json_response(
        {"error": {"type": "invalid_request_error",
                   "message": f"n must be an integer in [1, 8], got {n!r}"}},
        status=400,
      )
    # Bounded admission (XOT_MAX_INFLIGHT, default 0 = off): acquire a slot
    # before the request touches the ring. Over the inflight cap it WAITS in
    # the bounded FIFO (firing the anticipatory host-tier prefix prefetch the
    # moment it queues — the PRESERVE queue-lookahead); past the queue bound
    # it is shed as HTTP 429 + Retry-After/queue position, which is how
    # overload stops surfacing as watchdog "stalled" aborts (PR 8 finding).
    # One slot covers the whole HTTP request: all n sub-completions and any
    # transparent restart run under it.
    gate = self.node.admission
    held_slot = False
    if gate.enabled:
      try:
        held_slot = await gate.acquire(
          request_id,
          on_queued=lambda: spawn_detached(self.node.prefetch_prompt(shard, prompt)))
      except AdmissionRejected as e:
        retry_after = max(1, int(math.ceil(e.retry_after_s)))
        return web.json_response(
          {"error": {
            "type": "rate_limit_error", "code": "overloaded",
            "message": f"admission queue is full ({e.queued}/{e.limit} waiting); "
                       f"retry in ~{retry_after}s",
            "queue_depth": e.queued, "queue_limit": e.limit,
            "queue_position": e.queued + 1, "est_wait_s": e.retry_after_s,
          }},
          status=429, headers={
            "Retry-After": str(retry_after),
            # A shed request consumed the whole budget by definition:
            # remaining 0, reset = the wait the client was quoted.
            **self._ratelimit_headers(remaining=0, reset_s=e.retry_after_s),
          })
    # One-shot transparent restart (XOT_REQUEST_RESTARTS, default 0 = off):
    # a request killed by a transient ring failure (hop error, stall
    # abort, evicted peer) is resubmitted ONCE under a fresh request id
    # (cold prefill) on the healed ring instead of surfacing a 500.
    # Streaming requests qualify only until their first byte reaches the
    # client (_stream_response prepares lazily and raises _StreamRestart
    # pre-first-write): once content is on the wire a restart could
    # contradict it. Deadline-respecting: no restart once
    # XOT_REQUEST_DEADLINE_S of wall time is spent.
    restart_budget = max(0, knobs.get_int("XOT_REQUEST_RESTARTS"))
    deadline_s = knobs.get_float("XOT_REQUEST_DEADLINE_S")
    # Snapshotted AT ADMISSION (slot held, queue position known): the
    # budget view every response from this request reports, streamed or not.
    rl_headers = self._ratelimit_headers()
    t0 = time.monotonic()
    base_request_id = request_id
    all_rids: List[str] = []
    try:
      attempt = 0
      while True:
        request_ids = [base_request_id] if n == 1 else [f"{base_request_id}#{i}" for i in range(n)]
        all_rids.extend(request_ids)
        for rid in request_ids:
          self.token_queues[rid] = asyncio.Queue()
        for rid in request_ids:
          await self.node.process_prompt(shard, prompt, rid, max_tokens=max_tokens, images=images,
                                         temperature=temperature, top_p=top_p,
                                         sampling=sampling or None)
        if stream:
          can_restart = (attempt < restart_budget
                         and (deadline_s <= 0 or time.monotonic() - t0 < deadline_s))
          try:
            return await self._stream_response(request, request_ids, model, tokenizer, stop=stop,
                                               logprobs=bool(want_logprobs),
                                               restartable=can_restart,
                                               extra_headers=rl_headers)
          except _StreamRestart as e:
            attempt += 1
            base_request_id = await self._restart_request(base_request_id, e.error)
            continue
        eos_ids = self._eos_ids(tokenizer)
        try:
          results = await asyncio.gather(*(
            self._await_completion(rid, tokenizer, eos_ids, stop) for rid in request_ids
          ))
        except asyncio.TimeoutError:
          return web.json_response({"detail": "Response timed out"}, status=408)
        error = next((err for _, err in results if err), None)
        if (error is not None and attempt < restart_budget and self._restartable(error)
            and (deadline_s <= 0 or time.monotonic() - t0 < deadline_s)):
          attempt += 1
          base_request_id = await self._restart_request(base_request_id, error)
          continue
        resp = self._build_full_response(request_ids, results, error, model, tokenizer, prompt,
                                         eos_ids, stop=stop, logprobs=bool(want_logprobs))
        resp.headers.update(rl_headers)
        return resp
    finally:
      if held_slot:
        # The slot outlives every sub-request and restart attempt; release
        # wakes the oldest queued waiter.
        gate.release()
      for rid in all_rids:
        self.token_queues.pop(rid, None)
        self.prev_token_lens.pop(rid, None)
        # A sub-request abandoned early (peer error, timeout, client gone,
        # a later sibling's process_prompt raising) must not keep decoding
        # to the cap with nobody listening. Idempotent: finished requests
        # no-op.
        try:
          await self.node.cancel_request(rid)
        except Exception as e:
          if DEBUG >= 1:
            print(f"[{rid}] post-response cancel failed: {e!r}")

  @staticmethod
  def _restartable(error: str) -> bool:
    # Client errors and blown deadlines are final; infra failures (hop
    # errors, stalls, evicted peers) qualify for the one-shot restart.
    return not error.startswith(("context_length_exceeded", "deadline_exceeded"))

  async def _restart_request(self, base_request_id: str, error: str) -> str:
    """Shared restart bookkeeping for the streaming and non-streaming
    branches: count it, heal the ring (one failed health check is enough to
    evict after a request just died there), return the fresh request id the
    resubmission runs under (cold prefill — no partial state survives)."""
    self.node.metrics.request_restarts_total.inc()
    if DEBUG >= 1:
      print(f"restarting request {base_request_id} after: {error}")
    try:
      await self.node.heal_ring()
    except Exception as e:
      if DEBUG >= 1:
        print(f"ring heal before restart failed: {e!r}")
    return str(uuid.uuid4())

  async def _tokenizer_for(self, model: str, shard):
    if model.startswith("synthetic") or model == "dummy":
      from xotorch_tpu.inference.tokenizers import DummyTokenizer
      return DummyTokenizer()
    # The engine resolves its tokenizer from the local model dir at load time;
    # reuse it when it serves the same model — no duplicate load, and no
    # network dependency in offline deployments.
    engine = self.node.inference_engine
    engine_shard = getattr(engine, "shard", None)
    engine_tok = getattr(engine, "tokenizer", None)
    if engine_tok is not None and engine_shard is not None and engine_shard.model_id == model:
      return engine_tok
    target = get_repo(model, self.inference_engine_classname)
    if self.node.shard_downloader is not None:
      try:
        local = await self.node.shard_downloader.ensure_shard(shard, self.inference_engine_classname)
        return await resolve_tokenizer(local)
      except Exception as e:
        # Fall through to resolving from the hub repo id below.
        if DEBUG >= 1:
          print(f"local tokenizer resolve for {model} failed ({e!r}); trying {target}")
    return await resolve_tokenizer(target)

  def _delta_tokens(self, request_id: str, tokens: List[int]) -> List[int]:
    prev = self.prev_token_lens.get(request_id, 0)
    self.prev_token_lens[request_id] = len(tokens)
    return tokens[prev:]

  def _chunk(self, request_id: str, model: str, content: str, finish_reason: Optional[str],
             index: int = 0, logprobs: Optional[dict] = None) -> dict:
    return {
      "id": f"chatcmpl-{request_id.split('#')[0]}",
      "object": "chat.completion.chunk",
      "created": int(time.time()),
      "model": model,
      "choices": [{
        "index": index,
        "delta": {"role": "assistant", "content": content} if content else {},
        "logprobs": logprobs,
        "finish_reason": finish_reason,
      }],
    }

  def _logprob_content(self, tokenizer, token_ids: List[int], entries: list) -> list:
    """OpenAI logprobs content items for generated tokens: token text,
    logprob, UTF-8 bytes, and the top-K alternatives the sampler reported.
    `entries` come from the engine in sampling order, 1:1 with token_ids."""
    items = []
    for tid, ent in zip(token_ids, entries):
      text = tokenizer.decode([tid])
      tops = []
      for alt_id, alt_lp in ent.get("top", ()):
        alt_text = tokenizer.decode([alt_id])
        tops.append({"token": alt_text, "logprob": alt_lp,
                     "bytes": list(alt_text.encode("utf-8"))})
      items.append({"token": text, "logprob": ent["logprob"],
                    "bytes": list(text.encode("utf-8")), "top_logprobs": tops})
    return items

  def _eos_ids(self, tokenizer) -> set:
    # Whatever stops the node must classify as "stop" here: delegate to the
    # node's own EOS set (engine tokenizer + model cfg) and add the ids of
    # the tokenizer used for this request (may differ from the engine's).
    ids = set(self.node._eos_token_ids())
    eos = getattr(tokenizer, "eos_token_id", None)
    if eos is not None:
      ids.add(eos)
    return ids

  async def _stream_response(self, request, request_ids: List[str], model: str, tokenizer,
                             stop: Optional[List[str]] = None, logprobs: bool = False,
                             restartable: bool = False,
                             extra_headers: Optional[dict] = None):
    """SSE stream over one or more completions (OpenAI n): sub-requests'
    queues are merged and each chunk carries its choice index.

    The response is prepared LAZILY (first write sends the headers): until
    then nothing has reached the client, so a restartable ring failure can
    raise _StreamRestart and the caller's restart loop resubmits the whole
    request transparently — the streaming half of XOT_REQUEST_RESTARTS.
    After the first write the old semantics hold (error event, terminate).

    Stop-sequence scanning works on the TRUE decoded text: each iteration
    decodes a choice's full non-EOS token list and diffs against the
    previously decoded text (per-chunk decode concatenation diverges from
    the real decode for SentencePiece-family tokenizers, which strip each
    chunk's leading space — a stop with a space at a chunk boundary would
    never match). Decodes happen once per CHUNK, not per token, so total
    cost is O(n^2/chunk) — negligible at serving chunk sizes. Until a
    choice finishes, a tail of max(len(stop))-1 chars is held back so a
    stop split across chunks is caught before any of it reaches the
    client; `sent[i]` tracks what choice i emitted."""
    response = web.StreamResponse(
      status=200, headers={**self._sse_headers(), **(extra_headers or {})})
    prepared = False

    async def write(data: bytes) -> None:
      nonlocal prepared
      if not prepared:
        prepared = True
        await response.prepare(request)
      await response.write(data)

    eos_ids = self._eos_ids(tokenizer)
    acc = ["" for _ in request_ids]
    sent = [0 for _ in request_ids]
    done = [False for _ in request_ids]
    holdback = max((len(s) for s in stop), default=1) - 1 if stop else 0

    merged: asyncio.Queue = asyncio.Queue()

    def _pump(idx: int, rid: str):
      async def run():
        while True:
          payload, fin = await self.token_queues[rid].get()
          await merged.put((idx, rid, payload, fin))
          if fin:
            return
      return spawn_detached(run())

    pumps = [_pump(i, rid) for i, rid in enumerate(request_ids)]
    try:
      deadline = time.monotonic() + self.response_timeout
      while not all(done):
        timeout = max(0.1, deadline - time.monotonic())
        idx, rid, tokens, finished = await asyncio.wait_for(merged.get(), timeout=timeout)
        if done[idx]:
          continue  # straggler after a stop-sequence cut
        error = self.node.request_errors.pop(rid, None) if finished else None
        if error is not None:
          if restartable and not prepared and self._restartable(error):
            # No byte has reached the client yet: hand the failure to the
            # restart loop instead of committing an error stream.
            raise _StreamRestart(error)
          # Mid-stream failure: OpenAI-style error event, then terminate. A
          # prompt that overflowed the KV budget is the client's error
          # (context_length_exceeded), not a server fault.
          etype = ("invalid_request_error" if error.startswith("context_length_exceeded")
                   else "server_error")
          payload = {"error": {"type": etype, "message": error}}
          await write(f"data: {json.dumps(payload)}\n\n".encode())
          done = [True] * len(done)
          break
        delta = self._delta_tokens(rid, tokens)
        finish_reason = None
        if finished:
          finish_reason = "stop" if (delta and delta[-1] in eos_ids) else "length"
        if stop:
          non_eos = [t for t in tokens if t not in eos_ids]
          full_text = tokenizer.decode(non_eos) if non_eos else ""
          scan_from = max(0, len(acc[idx]) - holdback)
          if len(full_text) >= len(acc[idx]):
            acc[idx] = full_text  # an empty finish signal must not wipe the text
          cut = min((i for i in (acc[idx].find(s, scan_from) for s in stop) if i >= 0), default=-1)
          if cut >= 0:
            content, finished, finish_reason = acc[idx][sent[idx]:cut], True, "stop"
            await self.node.cancel_request(rid)
          else:
            emit_to = len(acc[idx]) if finished else max(sent[idx], len(acc[idx]) - holdback)
            content = acc[idx][sent[idx]:emit_to]
          sent[idx] += len(content)
        else:
          new_tokens = [t for t in delta if t not in eos_ids]
          content = tokenizer.decode(new_tokens) if new_tokens else ""
        lp_obj = None
        if logprobs and not stop and delta:
          # Token-aligned streaming: drain exactly this delta's entries.
          # (Stop-sequence streams emit CHARACTER slices that cross token
          # boundaries, so per-chunk logprobs are omitted there.)
          entries = self.node.pop_request_logprobs(rid, len(delta))
          if entries is not None:
            pairs = [(t, e) for t, e in zip(delta, entries) if t not in eos_ids]
            lp_obj = {"content": self._logprob_content(
              tokenizer, [p[0] for p in pairs], [p[1] for p in pairs])}
        done[idx] = done[idx] or finished
        chunk = self._chunk(rid, model, content, finish_reason, index=idx, logprobs=lp_obj)
        await write(f"data: {json.dumps(chunk)}\n\n".encode())
        deadline = time.monotonic() + self.response_timeout
      await write(b"data: [DONE]\n\n")
      await response.write_eof()
      return response
    except asyncio.TimeoutError:
      for idx, rid in enumerate(request_ids):
        if not done[idx]:
          chunk = self._chunk(rid, model, "", "length", index=idx)
          await write(f"data: {json.dumps(chunk)}\n\n".encode())
      await write(b"data: [DONE]\n\n")
      await response.write_eof()
      return response
    finally:
      for p in pumps:
        p.cancel()

  async def _await_completion(self, request_id: str, tokenizer, eos_ids: set,
                              stop: Optional[List[str]]):
    """Collect one sub-request's full token list. Returns (tokens, error).
    Raises asyncio.TimeoutError on stall."""
    tokens: List[int] = []
    finished = False
    cancel_sent = False
    scanned_len = 0
    deadline = time.monotonic() + self.response_timeout
    while not finished:
      timeout = max(0.1, deadline - time.monotonic())
      payload, finished = await asyncio.wait_for(self.token_queues[request_id].get(), timeout=timeout)
      if len(payload) >= len(tokens):
        tokens = payload  # an empty finish signal must not wipe the completion
      if stop and not cancel_sent and not finished and len(tokens) > scanned_len:
        # Stop already reached: cancel generation instead of running to the
        # cap; the cancel surfaces as the finished signal. Scan the NEW
        # payload delta plus a stop-sized token overlap (a stop of C chars
        # spans at most C tokens) — a full re-decode per payload would be
        # O(n^2) on the event loop every request shares.
        overlap = max(len(s) for s in stop)
        window = [t for t in tokens[max(0, scanned_len - overlap):] if t not in eos_ids]
        scanned_len = len(tokens)
        text = tokenizer.decode(window)
        if any(s in text for s in stop):
          cancel_sent = True
          await self.node.cancel_request(request_id)
      deadline = time.monotonic() + self.response_timeout
    return tokens, self.node.request_errors.pop(request_id, None)

  def _build_full_response(self, request_ids: List[str], results, error: Optional[str],
                           model: str, tokenizer, prompt: str, eos_ids: set,
                           stop: Optional[List[str]] = None, logprobs: bool = False):
    """Build the JSON completion from collected sub-request results (the
    gather lives in handle_post_chat_completions so its restart loop can
    inspect the error before a response is committed)."""
    if error is not None:
      if error.startswith("context_length_exceeded"):
        # The prompt didn't fit the model's KV budget — 400, like OpenAI's
        # context-length error, not a 500 (ADVICE r1 (d)).
        return web.json_response(
          {"error": {"type": "invalid_request_error", "code": "context_length_exceeded",
                     "message": error}}, status=400
        )
      return web.json_response(
        {"error": {"type": "server_error", "message": error}}, status=500
      )
    choices = []
    total_completion = 0
    for idx, (tokens, _) in enumerate(results):
      finish_reason = "stop" if (tokens and tokens[-1] in eos_ids) else "length"
      content_tokens = [t for t in tokens if t not in eos_ids]
      content = tokenizer.decode(content_tokens) if content_tokens else ""
      stop_cut = False
      if stop:
        cut = min((i for i in (content.find(s) for s in stop) if i >= 0), default=-1)
        if cut >= 0:
          # OpenAI semantics: the completion ends BEFORE the stop sequence.
          content, finish_reason, stop_cut = content[:cut], "stop", True
          if content and hasattr(tokenizer, "encode"):
            content_tokens = tokenizer.encode(content)
          elif not content:
            content_tokens = []
      total_completion += len(content_tokens)
      lp_obj = None
      if logprobs:
        # Entries arrive from the engine in sampling order, 1:1 with the
        # buffered tokens; EOS rows are dropped with their tokens. None (vs
        # empty) when the sampler ran on a remote ring node — the token
        # broadcast carries ids only.
        entries = self.node.pop_request_logprobs(request_ids[idx])
        if entries is not None:
          pairs = [(t, e) for t, e in zip(tokens, entries) if t not in eos_ids]
          if stop_cut:
            # Truncate at the SAMPLED-token boundary: keep tokens until
            # their decode covers the kept text (a re-encode of the cut
            # text can tokenize differently from what was sampled, so
            # len(content_tokens) is not a valid pair count here).
            kept: list = []
            for pair in pairs:
              if len(tokenizer.decode([p[0] for p in kept])) >= len(content):
                break
              kept.append(pair)
            pairs = kept
          lp_obj = {"content": self._logprob_content(
            tokenizer, [p[0] for p in pairs], [p[1] for p in pairs])}
      choices.append({
        "index": idx,
        "message": {"role": "assistant", "content": content},
        "logprobs": lp_obj,
        "finish_reason": finish_reason,
      })
    prompt_tokens = len(tokenizer.encode(prompt)) if hasattr(tokenizer, "encode") else 0
    return web.json_response({
      "id": f"chatcmpl-{request_ids[0].split('#')[0]}",
      "object": "chat.completion",
      "created": int(time.time()),
      "model": model,
      "choices": choices,
      "usage": {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": total_completion,
        "total_tokens": prompt_tokens + total_completion,
      },
    })

  # ------------------------------------------------------------ lifecycle

  async def run(self, host: str = "0.0.0.0", port: int = 52415):
    runner = web.AppRunner(self.app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    if DEBUG >= 0:
      print(f"ChatGPT-compatible API on http://{host}:{port}")
    return runner
