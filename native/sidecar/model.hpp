// Sharded transformer forward in plain C++ (fp32 compute, threaded GEMV).
//
// This is the compute core of the native sidecar — the TPU build's equivalent
// of the reference's out-of-process "cheetah" C++ engine
// (xotorch/inference/cheetah/sharded_inference_engine.py describes only the
// client; the service itself lived out of repo — SURVEY §2.6.3). Here the
// service is IN-repo: it loads an HF-layout safetensors checkpoint filtered
// to a layer-range Shard, keeps a per-session KV cache resident across calls
// (the wire carries only (tokens|hidden, pos) — never masks or token
// history), and serves dense llama / mistral / qwen2 / qwen3 families.
//
// Numerics match the JAX engine's model (xotorch_tpu/models/transformer.py):
// RMSNorm, HF rotate-half RoPE with optional llama3 frequency scaling, GQA
// attention, SwiGLU MLP, optional qwen2 attention bias and qwen3 per-head
// q/k RMSNorm — so the split-vs-full logits-equivalence invariant
// (test_inference_engine.py:43-44 in the reference) holds across engines.
#pragma once

#include <atomic>
#include <cmath>
#include <cstdlib>
#include <condition_variable>
#include <cstring>
#include <fstream>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "json.hpp"
#include "safetensors.hpp"

namespace xot {

// ------------------------------------------------------------- thread pool

class ThreadPool {
 public:
  explicit ThreadPool(int n_threads) {
    if (n_threads <= 0) n_threads = 1;
    for (int i = 0; i < n_threads; ++i) {
      workers_.emplace_back([this] { worker(); });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  // Blocking parallel for over [0, n) in contiguous chunks.
  void parallel_for(int64_t n, const std::function<void(int64_t, int64_t)>& fn) {
    int64_t n_workers = static_cast<int64_t>(workers_.size());
    if (n <= 1 || n_workers <= 1) {
      fn(0, n);
      return;
    }
    int64_t chunks = std::min(n, n_workers);
    int64_t chunk = (n + chunks - 1) / chunks;
    std::atomic<int64_t> remaining{chunks};
    std::mutex done_mu;
    std::condition_variable done_cv;
    for (int64_t c = 0; c < chunks; ++c) {
      int64_t begin = c * chunk, end = std::min(n, begin + chunk);
      enqueue([&, begin, end] {
        fn(begin, end);
        if (remaining.fetch_sub(1) == 1) {
          std::lock_guard<std::mutex> lk(done_mu);
          done_cv.notify_one();
        }
      });
    }
    std::unique_lock<std::mutex> lk(done_mu);
    done_cv.wait(lk, [&] { return remaining.load() == 0; });
  }

  int size() const { return static_cast<int>(workers_.size()); }

 private:
  void enqueue(std::function<void()> task) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      tasks_.push_back(std::move(task));
    }
    cv_.notify_one();
  }

  void worker() {
    while (true) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
        if (stop_ && tasks_.empty()) return;
        task = std::move(tasks_.front());
        tasks_.erase(tasks_.begin());
      }
      task();
    }
  }

  std::vector<std::thread> workers_;
  std::vector<std::function<void()>> tasks_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_ = false;
};

// ------------------------------------------------------------------ config

struct ModelConfig {
  std::string family = "llama";  // llama | mistral | qwen2 | qwen3
  int64_t vocab_size = 32000;
  int64_t hidden_size = 4096;
  int64_t num_layers = 32;
  int64_t num_heads = 32;
  int64_t num_kv_heads = 32;
  int64_t head_dim = 128;
  int64_t intermediate_size = 11008;
  float rms_norm_eps = 1e-5f;
  float rope_theta = 10000.0f;
  bool rope_llama3 = false;
  float rope_factor = 32.0f;
  float rope_low_freq_factor = 1.0f;
  float rope_high_freq_factor = 4.0f;
  int64_t rope_original_max_pos = 8192;
  int64_t max_seq_len = 8192;
  bool tie_word_embeddings = false;
  bool attention_bias = false;
  bool qk_norm = false;

  static ModelConfig from_file(const std::string& path) {
    std::ifstream in(path);
    if (!in) throw std::runtime_error("config: cannot open " + path);
    std::stringstream ss;
    ss << in.rdbuf();
    JsonPtr root = JsonParser::parse(ss.str());
    // Multimodal configs nest the decoder under text_config (config.py:59-63).
    JsonPtr j = root->has("text_config") ? root->at("text_config") : root;

    ModelConfig c;
    std::string model_type = j->str("model_type", "llama");
    if (model_type == "mistral") c.family = "mistral";
    else if (model_type == "qwen2") c.family = "qwen2";
    else if (model_type == "qwen3" || model_type == "qwen3_moe") c.family = "qwen3";
    else c.family = "llama";

    c.num_heads = j->integer("num_attention_heads", 32);
    c.hidden_size = j->integer("hidden_size", 4096);
    c.head_dim = j->integer("head_dim", c.hidden_size / c.num_heads);
    if (c.head_dim == 0) c.head_dim = c.hidden_size / c.num_heads;
    c.vocab_size = j->integer("vocab_size", 32000);
    c.num_layers = j->integer("num_hidden_layers", 32);
    c.num_kv_heads = j->integer("num_key_value_heads", c.num_heads);
    c.intermediate_size = j->integer("intermediate_size", 11008);
    c.rms_norm_eps = static_cast<float>(j->num("rms_norm_eps", 1e-5));
    c.rope_theta = static_cast<float>(j->num("rope_theta", 10000.0));
    c.max_seq_len = j->integer("max_position_embeddings", 8192);
    c.tie_word_embeddings = j->boolean("tie_word_embeddings", false);
    c.attention_bias = j->boolean("attention_bias", model_type == "qwen2");
    c.qk_norm = (c.family == "qwen3");
    if (j->has("rope_scaling") && j->at("rope_scaling")->is_object()) {
      auto rs = j->at("rope_scaling");
      std::string rt = rs->str("rope_type", rs->str("type", ""));
      if (rt == "llama3") {
        c.rope_llama3 = true;
        c.rope_factor = static_cast<float>(rs->num("factor", 32.0));
        c.rope_low_freq_factor = static_cast<float>(rs->num("low_freq_factor", 1.0));
        c.rope_high_freq_factor = static_cast<float>(rs->num("high_freq_factor", 4.0));
        c.rope_original_max_pos = rs->integer("original_max_position_embeddings", 8192);
      }
    }
    return c;
  }
};

// ----------------------------------------------------------------- weights

// int8 weight-only quantization of one linear: per-out-row symmetric scale
// (the JAX engine's per-channel layout, models/quantize.py). CPU GEMV is
// memory-bandwidth-bound, so streaming 1 byte/weight instead of 4 is the
// dominant win; accumulation stays fp32. Enabled via XOT_SIDECAR_QUANT=int8
// (the fp32 rows are freed after conversion — 4x less resident memory).
struct QLin {
  std::vector<int8_t> q;   // [out, in] row-major
  std::vector<float> s;    // [out]
  bool used() const { return !q.empty(); }
};

struct LayerWeights {
  // Linears kept in HF [out, in] row-major: GEMV walks rows contiguously.
  std::vector<float> wq, wk, wv, wo;          // [out, hidden]
  std::vector<float> bq, bk, bv;              // optional qwen2 bias
  std::vector<float> attn_norm, mlp_norm;     // [hidden]
  std::vector<float> q_norm, k_norm;          // optional qwen3 [head_dim]
  std::vector<float> w_gate, w_up, w_down;    // SwiGLU
  QLin qwq, qwk, qwv, qwo, qgate, qup, qdown; // int8 twins (XOT_SIDECAR_QUANT)
};

struct ShardWeights {
  std::vector<LayerWeights> layers;
  std::vector<float> embed;       // [vocab, hidden] (first shard, or tied last)
  std::vector<float> final_norm;  // [hidden] (last shard)
  std::vector<float> lm_head;     // [vocab, hidden] (last shard; = embed if tied)
  bool has_embed = false, has_head = false;
};

// -------------------------------------------------------------- kv session

struct Session {
  // cache[l] is [max_len, n_kv*head_dim] for k and v.
  std::vector<std::vector<float>> k, v;
  int64_t pos = 0;
  int64_t last_used_ns = 0;
};

// ------------------------------------------------------------------- model

class ShardModel {
 public:
  ShardModel(const std::string& model_dir, int64_t start_layer, int64_t end_layer,
             int64_t cache_len, ThreadPool* pool)
      : cfg_(ModelConfig::from_file(model_dir + "/config.json")),
        start_layer_(start_layer),
        end_layer_(end_layer),
        pool_(pool) {
    cache_len_ = std::min(cache_len, cfg_.max_seq_len);
    is_first_ = start_layer_ == 0;
    is_last_ = end_layer_ == cfg_.num_layers - 1;
    const char* qenv = std::getenv("XOT_SIDECAR_QUANT");
    quant_int8_ = qenv != nullptr && std::string(qenv) == "int8";
    load_weights(model_dir);
  }

  const ModelConfig& config() const { return cfg_; }
  bool is_first() const { return is_first_; }
  bool is_last() const { return is_last_; }
  int64_t cache_len() const { return cache_len_; }
  int64_t n_layers() const { return end_layer_ - start_layer_ + 1; }

  Session new_session() const {
    Session s;
    int64_t kv_dim = cfg_.num_kv_heads * cfg_.head_dim;
    s.k.resize(n_layers());
    s.v.resize(n_layers());
    for (int64_t l = 0; l < n_layers(); ++l) {
      s.k[l].assign(static_cast<size_t>(cache_len_ * kv_dim), 0.0f);
      s.v[l].assign(static_cast<size_t>(cache_len_ * kv_dim), 0.0f);
    }
    return s;
  }

  // tokens path (first shard): [T] ids -> hidden or logits [T, out_dim]
  // hidden path (mid/last shard): [T, hidden] -> hidden or logits.
  // Returns [T, hidden] (not last) or [T, vocab] (last).
  std::vector<float> forward_tokens(Session& s, const std::vector<int32_t>& tokens) {
    int64_t T = static_cast<int64_t>(tokens.size());
    std::vector<float> x(static_cast<size_t>(T * cfg_.hidden_size));
    for (int64_t t = 0; t < T; ++t) {
      int64_t id = tokens[static_cast<size_t>(t)];
      if (id < 0 || id >= cfg_.vocab_size) throw std::runtime_error("token id out of range");
      std::memcpy(&x[t * cfg_.hidden_size], &w_.embed[id * cfg_.hidden_size], cfg_.hidden_size * 4);
    }
    return forward_hidden(s, x, T);
  }

  std::vector<float> forward_hidden(Session& s, std::vector<float> x, int64_t T) {
    if (s.pos + T > cache_len_)
      throw std::runtime_error("kv cache overflow: pos " + std::to_string(s.pos) + " + " + std::to_string(T) + " > " + std::to_string(cache_len_));
    for (int64_t l = 0; l < n_layers(); ++l) layer_forward(s, l, x, T);
    s.pos += T;
    if (!is_last_) return x;

    // Final norm + LM head.
    int64_t H = cfg_.hidden_size, V = cfg_.vocab_size;
    std::vector<float> normed = x;
    for (int64_t t = 0; t < T; ++t) rmsnorm(&normed[t * H], w_.final_norm.data(), H);
    std::vector<float> logits(static_cast<size_t>(T * V));
    const std::vector<float>& head = w_.has_head ? w_.lm_head : w_.embed;
    for (int64_t t = 0; t < T; ++t)
      gemv(head.data(), &normed[t * H], &logits[t * V], V, H, nullptr);
    return logits;
  }

 private:
  // y[o] = w[o,:] . x  (+bias), threaded over output rows.
  void gemv(const float* w, const float* x, float* y, int64_t out_dim, int64_t in_dim,
            const float* bias) {
    pool_->parallel_for(out_dim, [&](int64_t begin, int64_t end) {
      for (int64_t o = begin; o < end; ++o) {
        const float* row = w + o * in_dim;
        float acc = 0.0f;
        for (int64_t i = 0; i < in_dim; ++i) acc += row[i] * x[i];
        y[o] = bias ? acc + bias[o] : acc;
      }
    });
  }

  // int8 GEMV: row dot in fp32 over int8 weights, per-row scale after.
  void gemv_q8(const QLin& l, const float* x, float* y, int64_t out_dim, int64_t in_dim,
               const float* bias) {
    pool_->parallel_for(out_dim, [&](int64_t begin, int64_t end) {
      for (int64_t o = begin; o < end; ++o) {
        const int8_t* row = l.q.data() + o * in_dim;
        float acc = 0.0f;
        for (int64_t i = 0; i < in_dim; ++i) acc += static_cast<float>(row[i]) * x[i];
        acc *= l.s[static_cast<size_t>(o)];
        y[o] = bias ? acc + bias[o] : acc;
      }
    });
  }

  // Dispatch: the int8 twin when present, fp32 rows otherwise.
  void lin(const std::vector<float>& w, const QLin& ql, const float* x, float* y,
           int64_t out_dim, int64_t in_dim, const float* bias) {
    if (ql.used()) gemv_q8(ql, x, y, out_dim, in_dim, bias);
    else gemv(w.data(), x, y, out_dim, in_dim, bias);
  }

  // Symmetric per-out-row int8 conversion; frees the fp32 rows. Rows are
  // independent — threaded over the pool so multi-GB loads convert at
  // memory speed instead of one core.
  void quantize_rows(std::vector<float>& w, QLin& out, int64_t out_dim,
                     int64_t in_dim) {
    out.q.resize(w.size());
    out.s.resize(static_cast<size_t>(out_dim));
    pool_->parallel_for(out_dim, [&](int64_t begin, int64_t end) {
      for (int64_t o = begin; o < end; ++o) {
        const float* row = &w[o * in_dim];
        float m = 0.0f;
        for (int64_t i = 0; i < in_dim; ++i) m = std::max(m, std::fabs(row[i]));
        float s = m > 0.0f ? m / 127.0f : 1.0f;
        out.s[static_cast<size_t>(o)] = s;
        int8_t* qrow = out.q.data() + o * in_dim;
        for (int64_t i = 0; i < in_dim; ++i)
          qrow[i] = static_cast<int8_t>(std::lrintf(row[i] / s));
      }
    });
    w.clear();
    w.shrink_to_fit();
  }

  void rmsnorm(float* x, const float* weight, int64_t n) const {
    float ss = 0.0f;
    for (int64_t i = 0; i < n; ++i) ss += x[i] * x[i];
    float inv = 1.0f / std::sqrt(ss / static_cast<float>(n) + cfg_.rms_norm_eps);
    for (int64_t i = 0; i < n; ++i) x[i] = x[i] * inv * weight[i];
  }

  // HF rotate-half RoPE with optional llama3 scaling (ops/rope.py parity).
  float scaled_inv_freq(int64_t i) const {
    int64_t D = cfg_.head_dim;
    float inv_freq = std::pow(cfg_.rope_theta, -2.0f * static_cast<float>(i) / static_cast<float>(D));
    if (!cfg_.rope_llama3) return inv_freq;
    const float two_pi = 6.283185307179586f;
    float wavelen = two_pi / inv_freq;
    float low_wavelen = static_cast<float>(cfg_.rope_original_max_pos) / cfg_.rope_low_freq_factor;
    float high_wavelen = static_cast<float>(cfg_.rope_original_max_pos) / cfg_.rope_high_freq_factor;
    if (wavelen > low_wavelen) return inv_freq / cfg_.rope_factor;
    if (wavelen < high_wavelen) return inv_freq;
    float smooth = (static_cast<float>(cfg_.rope_original_max_pos) / wavelen - cfg_.rope_low_freq_factor) /
                   (cfg_.rope_high_freq_factor - cfg_.rope_low_freq_factor);
    return (1.0f - smooth) * inv_freq / cfg_.rope_factor + smooth * inv_freq;
  }

  void rope(float* vec, int64_t pos) const {
    int64_t D = cfg_.head_dim, half = D / 2;
    for (int64_t i = 0; i < half; ++i) {
      float angle = static_cast<float>(pos) * scaled_inv_freq(i);
      float c = std::cos(angle), sn = std::sin(angle);
      float a = vec[i], b = vec[i + half];
      vec[i] = a * c - b * sn;
      vec[i + half] = b * c + a * sn;
    }
  }

  void layer_forward(Session& s, int64_t l, std::vector<float>& x, int64_t T) {
    const LayerWeights& lw = w_.layers[static_cast<size_t>(l)];
    int64_t H = cfg_.hidden_size, D = cfg_.head_dim;
    int64_t NH = cfg_.num_heads, NKV = cfg_.num_kv_heads;
    int64_t q_dim = NH * D, kv_dim = NKV * D;
    int64_t group = NH / NKV;
    float scale = 1.0f / std::sqrt(static_cast<float>(D));

    std::vector<float> q(static_cast<size_t>(T * q_dim));
    std::vector<float> attn_out(static_cast<size_t>(T * q_dim));

    for (int64_t t = 0; t < T; ++t) {
      int64_t pos = s.pos + t;
      std::vector<float> normed(static_cast<size_t>(H));
      std::memcpy(normed.data(), &x[t * H], H * 4);
      rmsnorm(normed.data(), lw.attn_norm.data(), H);

      float* qt = &q[t * q_dim];
      float* kt = &s.k[l][pos * kv_dim];
      float* vt = &s.v[l][pos * kv_dim];
      lin(lw.wq, lw.qwq, normed.data(), qt, q_dim, H, lw.bq.empty() ? nullptr : lw.bq.data());
      lin(lw.wk, lw.qwk, normed.data(), kt, kv_dim, H, lw.bk.empty() ? nullptr : lw.bk.data());
      lin(lw.wv, lw.qwv, normed.data(), vt, kv_dim, H, lw.bv.empty() ? nullptr : lw.bv.data());

      for (int64_t h = 0; h < NH; ++h) {
        if (cfg_.qk_norm) rmsnorm(qt + h * D, lw.q_norm.data(), D);
        rope(qt + h * D, pos);
      }
      for (int64_t h = 0; h < NKV; ++h) {
        if (cfg_.qk_norm) rmsnorm(kt + h * D, lw.k_norm.data(), D);
        rope(kt + h * D, pos);
      }
    }

    // Causal attention against the resident cache, threaded over heads.
    pool_->parallel_for(NH, [&](int64_t h_begin, int64_t h_end) {
      std::vector<float> scores;
      for (int64_t h = h_begin; h < h_end; ++h) {
        int64_t kvh = h / group;
        for (int64_t t = 0; t < T; ++t) {
          int64_t n_keys = s.pos + t + 1;
          scores.resize(static_cast<size_t>(n_keys));
          const float* qh = &q[t * q_dim + h * D];
          float max_s = -1e30f;
          for (int64_t j = 0; j < n_keys; ++j) {
            const float* kh = &s.k[l][j * kv_dim + kvh * D];
            float acc = 0.0f;
            for (int64_t d = 0; d < D; ++d) acc += qh[d] * kh[d];
            scores[j] = acc * scale;
            if (scores[j] > max_s) max_s = scores[j];
          }
          float denom = 0.0f;
          for (int64_t j = 0; j < n_keys; ++j) {
            scores[j] = std::exp(scores[j] - max_s);
            denom += scores[j];
          }
          float* out = &attn_out[t * q_dim + h * D];
          std::fill(out, out + D, 0.0f);
          float inv_denom = 1.0f / denom;
          for (int64_t j = 0; j < n_keys; ++j) {
            const float* vh = &s.v[l][j * kv_dim + kvh * D];
            float wgt = scores[j] * inv_denom;
            for (int64_t d = 0; d < D; ++d) out[d] += wgt * vh[d];
          }
        }
      }
    });

    // o-proj + residual, then SwiGLU MLP + residual.
    int64_t I = cfg_.intermediate_size;
    std::vector<float> proj(static_cast<size_t>(H));
    std::vector<float> gate(static_cast<size_t>(I)), up(static_cast<size_t>(I));
    for (int64_t t = 0; t < T; ++t) {
      lin(lw.wo, lw.qwo, &attn_out[t * q_dim], proj.data(), H, q_dim, nullptr);
      for (int64_t i = 0; i < H; ++i) x[t * H + i] += proj[i];

      std::vector<float> normed(static_cast<size_t>(H));
      std::memcpy(normed.data(), &x[t * H], H * 4);
      rmsnorm(normed.data(), lw.mlp_norm.data(), H);
      lin(lw.w_gate, lw.qgate, normed.data(), gate.data(), I, H, nullptr);
      lin(lw.w_up, lw.qup, normed.data(), up.data(), I, H, nullptr);
      for (int64_t i = 0; i < I; ++i) {
        float g = gate[i];
        gate[i] = (g / (1.0f + std::exp(-g))) * up[i];  // silu(g) * up
      }
      lin(lw.w_down, lw.qdown, gate.data(), proj.data(), H, I, nullptr);
      for (int64_t i = 0; i < H; ++i) x[t * H + i] += proj[i];
    }
  }

  void load_weights(const std::string& model_dir) {
    CheckpointDir ckpt(model_dir);
    // HF checkpoints prefix decoder tensors with "model." (weights.py:110-117).
    auto resolve = [&](const std::string& name) -> std::string {
      for (const char* prefix : {"", "model.", "language_model.model.", "language_model."}) {
        std::string full = std::string(prefix) + name;
        if (ckpt.has(full)) return full;
      }
      throw std::runtime_error("checkpoint: tensor not found under any prefix: " + name);
    };
    auto load = [&](const std::string& name) { return SafetensorsFile::to_f32(ckpt.at(resolve(name))); };
    auto maybe_load = [&](const std::string& name, std::vector<float>& dst) {
      for (const char* prefix : {"", "model.", "language_model.model.", "language_model."}) {
        std::string full = std::string(prefix) + name;
        if (ckpt.has(full)) {
          dst = SafetensorsFile::to_f32(ckpt.at(full));
          return true;
        }
      }
      return false;
    };

    w_.layers.resize(static_cast<size_t>(n_layers()));
    for (int64_t li = start_layer_; li <= end_layer_; ++li) {
      LayerWeights& lw = w_.layers[static_cast<size_t>(li - start_layer_)];
      std::string p = "layers." + std::to_string(li) + ".";
      lw.attn_norm = load(p + "input_layernorm.weight");
      lw.mlp_norm = load(p + "post_attention_layernorm.weight");
      lw.wq = load(p + "self_attn.q_proj.weight");
      lw.wk = load(p + "self_attn.k_proj.weight");
      lw.wv = load(p + "self_attn.v_proj.weight");
      lw.wo = load(p + "self_attn.o_proj.weight");
      if (cfg_.attention_bias) {
        maybe_load(p + "self_attn.q_proj.bias", lw.bq);
        maybe_load(p + "self_attn.k_proj.bias", lw.bk);
        maybe_load(p + "self_attn.v_proj.bias", lw.bv);
      }
      if (cfg_.qk_norm) {
        maybe_load(p + "self_attn.q_norm.weight", lw.q_norm);
        maybe_load(p + "self_attn.k_norm.weight", lw.k_norm);
      }
      lw.w_gate = load(p + "mlp.gate_proj.weight");
      lw.w_up = load(p + "mlp.up_proj.weight");
      lw.w_down = load(p + "mlp.down_proj.weight");
      if (quant_int8_) {
        int64_t H = cfg_.hidden_size, I = cfg_.intermediate_size;
        int64_t q_dim = cfg_.num_heads * cfg_.head_dim;
        int64_t kv_dim = cfg_.num_kv_heads * cfg_.head_dim;
        quantize_rows(lw.wq, lw.qwq, q_dim, H);
        quantize_rows(lw.wk, lw.qwk, kv_dim, H);
        quantize_rows(lw.wv, lw.qwv, kv_dim, H);
        quantize_rows(lw.wo, lw.qwo, H, q_dim);
        quantize_rows(lw.w_gate, lw.qgate, I, H);
        quantize_rows(lw.w_up, lw.qup, I, H);
        quantize_rows(lw.w_down, lw.qdown, H, I);
      }
    }
    if (is_first_ || (cfg_.tie_word_embeddings && is_last_)) {
      w_.has_embed = maybe_load("embed_tokens.weight", w_.embed);
      if (!w_.has_embed) throw std::runtime_error("checkpoint: embed_tokens.weight missing");
    }
    if (is_last_) {
      w_.final_norm = load("norm.weight");
      if (!cfg_.tie_word_embeddings) {
        w_.has_head = maybe_load("lm_head.weight", w_.lm_head);
        if (!w_.has_head && !w_.has_embed)
          throw std::runtime_error("checkpoint: neither lm_head nor tied embeddings present");
      }
    }
  }

  ModelConfig cfg_;
  int64_t start_layer_, end_layer_;
  bool quant_int8_ = false;
  int64_t cache_len_;
  bool is_first_ = false, is_last_ = false;
  ShardWeights w_;
  ThreadPool* pool_;
};

}  // namespace xot
