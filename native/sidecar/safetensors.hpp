// mmap-based safetensors reader.
//
// Format: 8-byte little-endian u64 header length, JSON header mapping tensor
// name -> {dtype, shape, data_offsets:[begin,end]} (offsets relative to the
// byte after the header), then the raw data region. Zero-copy: tensors are
// served as pointers into the mapping; dtype conversion happens at the
// consumer (model load), mirroring the Python side's one-pass-per-file read
// (xotorch_tpu/models/weights.py).
#pragma once

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "json.hpp"

namespace xot {

struct TensorView {
  std::string dtype;  // "F32" | "BF16" | "F16" | "I64" | ...
  std::vector<int64_t> shape;
  const uint8_t* data = nullptr;
  size_t nbytes = 0;

  int64_t numel() const {
    int64_t n = 1;
    for (int64_t d : shape) n *= d;
    return n;
  }
};

inline float bf16_to_f32(uint16_t v) {
  // Same <<16 widening the reference's client used on the wire
  // (cheetah/sharded_inference_engine.py:436-439).
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float out;
  std::memcpy(&out, &bits, sizeof(out));
  return out;
}

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, sizeof(bits));
  // Round-to-nearest-even, matching XLA's convert semantics.
  uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}

inline float f16_to_f32(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t mant = h & 0x3FF;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {  // subnormal: renormalize
      int shift = 0;
      while (!(mant & 0x400)) { mant <<= 1; ++shift; }
      mant &= 0x3FF;
      bits = sign | ((127 - 15 - shift + 1) << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  std::memcpy(&out, &bits, sizeof(out));
  return out;
}

class SafetensorsFile {
 public:
  explicit SafetensorsFile(const std::string& path) : path_(path) {
    fd_ = ::open(path.c_str(), O_RDONLY);
    if (fd_ < 0) throw std::runtime_error("safetensors: cannot open " + path);
    struct stat st;
    if (fstat(fd_, &st) != 0) throw std::runtime_error("safetensors: fstat failed for " + path);
    size_ = static_cast<size_t>(st.st_size);
    base_ = static_cast<const uint8_t*>(mmap(nullptr, size_, PROT_READ, MAP_PRIVATE, fd_, 0));
    if (base_ == MAP_FAILED) throw std::runtime_error("safetensors: mmap failed for " + path);

    uint64_t header_len = 0;
    std::memcpy(&header_len, base_, 8);  // little-endian per spec; x86/arm LE hosts
    if (8 + header_len > size_) throw std::runtime_error("safetensors: truncated header in " + path);
    std::string header(reinterpret_cast<const char*>(base_ + 8), header_len);
    JsonPtr j = JsonParser::parse(header);
    const uint8_t* data_region = base_ + 8 + header_len;
    for (auto& kv : j->obj_v) {
      if (kv.first == "__metadata__") continue;
      TensorView t;
      t.dtype = kv.second->str("dtype", "F32");
      for (auto& d : kv.second->at("shape")->arr_v) t.shape.push_back(static_cast<int64_t>(d->num_v));
      auto offs = kv.second->at("data_offsets");
      size_t begin = static_cast<size_t>(offs->arr_v[0]->num_v);
      size_t end = static_cast<size_t>(offs->arr_v[1]->num_v);
      t.data = data_region + begin;
      t.nbytes = end - begin;
      tensors_[kv.first] = t;
    }
  }

  ~SafetensorsFile() {
    if (base_ && base_ != MAP_FAILED) munmap(const_cast<uint8_t*>(base_), size_);
    if (fd_ >= 0) ::close(fd_);
  }

  SafetensorsFile(const SafetensorsFile&) = delete;
  SafetensorsFile& operator=(const SafetensorsFile&) = delete;

  bool has(const std::string& name) const { return tensors_.count(name) > 0; }
  const TensorView& at(const std::string& name) const {
    auto it = tensors_.find(name);
    if (it == tensors_.end()) throw std::runtime_error("safetensors: no tensor " + name + " in " + path_);
    return it->second;
  }
  const std::map<std::string, TensorView>& tensors() const { return tensors_; }

  // Convert any supported dtype to a contiguous f32 buffer.
  static std::vector<float> to_f32(const TensorView& t) {
    int64_t n = t.numel();
    std::vector<float> out(static_cast<size_t>(n));
    if (t.dtype == "F32") {
      std::memcpy(out.data(), t.data, n * 4);
    } else if (t.dtype == "BF16") {
      const uint16_t* src = reinterpret_cast<const uint16_t*>(t.data);
      for (int64_t i = 0; i < n; ++i) out[i] = bf16_to_f32(src[i]);
    } else if (t.dtype == "F16") {
      const uint16_t* src = reinterpret_cast<const uint16_t*>(t.data);
      for (int64_t i = 0; i < n; ++i) out[i] = f16_to_f32(src[i]);
    } else if (t.dtype == "F64") {
      const double* src = reinterpret_cast<const double*>(t.data);
      for (int64_t i = 0; i < n; ++i) out[i] = static_cast<float>(src[i]);
    } else {
      throw std::runtime_error("safetensors: unsupported dtype " + t.dtype);
    }
    return out;
  }

 private:
  std::string path_;
  int fd_ = -1;
  size_t size_ = 0;
  const uint8_t* base_ = nullptr;
  std::map<std::string, TensorView> tensors_;
};

// A model directory: resolves tensor name -> file via model.safetensors.index.json
// (sharded checkpoints) or a single model.safetensors, like weights.py:_index_for.
class CheckpointDir {
 public:
  explicit CheckpointDir(const std::string& dir) : dir_(dir) {
    std::string index_path = dir + "/model.safetensors.index.json";
    if (FILE* f = fopen(index_path.c_str(), "rb")) {
      std::string text = read_all(f);
      fclose(f);
      JsonPtr j = JsonParser::parse(text);
      for (auto& kv : j->at("weight_map")->obj_v) name_to_file_[kv.first] = kv.second->str_v;
    } else {
      std::string single = dir + "/model.safetensors";
      auto file = std::make_shared<SafetensorsFile>(single);
      files_["model.safetensors"] = file;
      for (auto& kv : file->tensors()) name_to_file_[kv.first] = "model.safetensors";
    }
  }

  bool has(const std::string& name) const { return name_to_file_.count(name) > 0; }

  const TensorView& at(const std::string& name) {
    auto it = name_to_file_.find(name);
    if (it == name_to_file_.end()) throw std::runtime_error("checkpoint: no tensor " + name);
    auto& file = files_[it->second];
    if (!file) file = std::make_shared<SafetensorsFile>(dir_ + "/" + it->second);
    return file->at(name);
  }

  std::vector<std::string> names() const {
    std::vector<std::string> out;
    out.reserve(name_to_file_.size());
    for (auto& kv : name_to_file_) out.push_back(kv.first);
    return out;
  }

 private:
  static std::string read_all(FILE* f) {
    std::string out;
    char buf[65536];
    size_t n;
    while ((n = fread(buf, 1, sizeof(buf), f)) > 0) out.append(buf, n);
    return out;
  }

  std::string dir_;
  std::map<std::string, std::string> name_to_file_;
  std::map<std::string, std::shared_ptr<SafetensorsFile>> files_;
};

}  // namespace xot
