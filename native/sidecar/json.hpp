// Minimal JSON parser/writer for the sidecar protocol and safetensors headers.
// Hand-rolled (no third-party deps in the image); supports the subset the
// framing + HF config.json + safetensors headers need: objects, arrays,
// strings (with \u escapes), numbers, bools, null.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace xot {

class Json;
using JsonPtr = std::shared_ptr<Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Type type = Type::Null;
  bool bool_v = false;
  double num_v = 0.0;
  std::string str_v;
  std::vector<JsonPtr> arr_v;
  std::map<std::string, JsonPtr> obj_v;

  static JsonPtr make(Type t) {
    auto j = std::make_shared<Json>();
    j->type = t;
    return j;
  }
  static JsonPtr of(double v) { auto j = make(Type::Number); j->num_v = v; return j; }
  static JsonPtr of(int64_t v) { return of(static_cast<double>(v)); }
  static JsonPtr of(const std::string& v) { auto j = make(Type::String); j->str_v = v; return j; }
  static JsonPtr of(bool v) { auto j = make(Type::Bool); j->bool_v = v; return j; }

  bool is_object() const { return type == Type::Object; }
  bool is_array() const { return type == Type::Array; }
  bool has(const std::string& k) const { return is_object() && obj_v.count(k) > 0; }

  JsonPtr at(const std::string& k) const {
    auto it = obj_v.find(k);
    if (it == obj_v.end()) throw std::runtime_error("json: missing key " + k);
    return it->second;
  }
  // Typed getters with defaults (config.json fields are frequently absent).
  double num(const std::string& k, double dflt) const {
    auto it = obj_v.find(k);
    return (it == obj_v.end() || it->second->type != Type::Number) ? dflt : it->second->num_v;
  }
  int64_t integer(const std::string& k, int64_t dflt) const {
    return static_cast<int64_t>(num(k, static_cast<double>(dflt)));
  }
  std::string str(const std::string& k, const std::string& dflt) const {
    auto it = obj_v.find(k);
    return (it == obj_v.end() || it->second->type != Type::String) ? dflt : it->second->str_v;
  }
  bool boolean(const std::string& k, bool dflt) const {
    auto it = obj_v.find(k);
    return (it == obj_v.end() || it->second->type != Type::Bool) ? dflt : it->second->bool_v;
  }

  void set(const std::string& k, JsonPtr v) { type = Type::Object; obj_v[k] = v; }

  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

 private:
  static void write_escaped(std::ostringstream& os, const std::string& s) {
    os << '"';
    for (char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  void write(std::ostringstream& os) const {
    switch (type) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_v ? "true" : "false"); break;
      case Type::Number: {
        if (num_v == static_cast<int64_t>(num_v)) os << static_cast<int64_t>(num_v);
        else os << num_v;
        break;
      }
      case Type::String: write_escaped(os, str_v); break;
      case Type::Array: {
        os << '[';
        for (size_t i = 0; i < arr_v.size(); ++i) {
          if (i) os << ',';
          arr_v[i]->write(os);
        }
        os << ']';
        break;
      }
      case Type::Object: {
        os << '{';
        bool first = true;
        for (auto& kv : obj_v) {
          if (!first) os << ',';
          first = false;
          write_escaped(os, kv.first);
          os << ':';
          kv.second->write(os);
        }
        os << '}';
        break;
      }
    }
  }
};

class JsonParser {
 public:
  static JsonPtr parse(const std::string& text) {
    JsonParser p(text);
    JsonPtr v = p.value();
    p.skip_ws();
    if (p.pos_ != p.text_.size()) throw std::runtime_error("json: trailing data");
    return v;
  }

 private:
  explicit JsonParser(const std::string& text) : text_(text) {}

  const std::string& text_;
  size_t pos_ = 0;

  void skip_ws() {
    while (pos_ < text_.size() && (text_[pos_] == ' ' || text_[pos_] == '\t' || text_[pos_] == '\n' || text_[pos_] == '\r'))
      ++pos_;
  }
  char peek() {
    if (pos_ >= text_.size()) throw std::runtime_error("json: unexpected end");
    return text_[pos_];
  }
  char next() {
    char c = peek();
    ++pos_;
    return c;
  }
  void expect(char c) {
    if (next() != c) throw std::runtime_error(std::string("json: expected '") + c + "'");
  }

  JsonPtr value() {
    skip_ws();
    char c = peek();
    if (c == '{') return object();
    if (c == '[') return array();
    if (c == '"') return Json::of(string_lit());
    if (c == 't' || c == 'f') return boolean();
    if (c == 'n') { literal("null"); return Json::make(Json::Type::Null); }
    return number();
  }

  void literal(const char* lit) {
    for (const char* p = lit; *p; ++p) expect(*p);
  }

  JsonPtr boolean() {
    if (peek() == 't') { literal("true"); return Json::of(true); }
    literal("false");
    return Json::of(false);
  }

  JsonPtr number() {
    size_t start = pos_;
    if (peek() == '-') ++pos_;
    while (pos_ < text_.size() && (isdigit(text_[pos_]) || text_[pos_] == '.' || text_[pos_] == 'e' || text_[pos_] == 'E' || text_[pos_] == '+' || text_[pos_] == '-'))
      ++pos_;
    return Json::of(std::stod(text_.substr(start, pos_ - start)));
  }

  std::string string_lit() {
    expect('"');
    std::string out;
    while (true) {
      char c = next();
      if (c == '"') break;
      if (c == '\\') {
        char e = next();
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            unsigned code = 0;
            for (int i = 0; i < 4; ++i) {
              char h = next();
              code <<= 4;
              if (h >= '0' && h <= '9') code |= h - '0';
              else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
              else throw std::runtime_error("json: bad \\u escape");
            }
            // UTF-8 encode (BMP only — enough for config/tokenizer metadata).
            if (code < 0x80) out += static_cast<char>(code);
            else if (code < 0x800) {
              out += static_cast<char>(0xC0 | (code >> 6));
              out += static_cast<char>(0x80 | (code & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (code >> 12));
              out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (code & 0x3F));
            }
            break;
          }
          default: throw std::runtime_error("json: bad escape");
        }
      } else {
        out += c;
      }
    }
    return out;
  }

  JsonPtr array() {
    expect('[');
    auto j = Json::make(Json::Type::Array);
    skip_ws();
    if (peek() == ']') { ++pos_; return j; }
    while (true) {
      j->arr_v.push_back(value());
      skip_ws();
      char c = next();
      if (c == ']') break;
      if (c != ',') throw std::runtime_error("json: expected , or ]");
    }
    return j;
  }

  JsonPtr object() {
    expect('{');
    auto j = Json::make(Json::Type::Object);
    skip_ws();
    if (peek() == '}') { ++pos_; return j; }
    while (true) {
      skip_ws();
      std::string key = string_lit();
      skip_ws();
      expect(':');
      j->obj_v[key] = value();
      skip_ws();
      char c = next();
      if (c == '}') break;
      if (c != ',') throw std::runtime_error("json: expected , or }");
    }
    return j;
  }
};

}  // namespace xot
