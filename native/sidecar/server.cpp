// xot-sidecar — the native (C++) out-of-process inference service.
//
// Fills the reference's "cheetah" slot (SURVEY §2.6.3): a Unix-domain-socket
// service speaking the same length-prefixed framing the reference's client
// used (cheetah/sharded_inference_engine.py:331-457) —
//
//   request:  4-byte BIG-ENDIAN header length ("!I") | UTF-8 JSON header |
//             raw concatenated tensor payload
//   response: identical framing
//
// — but with the service itself in-repo and the wire made bf16-clean: hidden
// states cross the socket as bf16 (uint16), not the reference's fp32 upcast
// (sharded_inference_engine.py:352). The KV cache stays resident per
// (session_id); each call carries only the new tokens or the incoming hidden
// segment. Commands:
//
//   {"cmd":"ping"}                                     -> {"status":"ok", ...}
//   {"cmd":"load","model_path":...,"layer_start":N,
//    "layer_end":N,"layer_total":N,"cache_len":N}      -> model + shard info
//   {"cmd":"infer","session_id":...,"input":
//    {"shape":[..],"dtype":"int32"|"float32"|"bfloat16"}} + payload
//                                                      -> output tensor
//   {"cmd":"reset","session_id":...}                   -> drop a session
//   {"cmd":"quit"}                                     -> shut down
//
// Build: `make -C native` (g++ -O3 -pthread, no external deps).
#include <arpa/inet.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <chrono>
#include <csignal>
#include <cstdio>
#include <cstring>
#include <string>

#include "model.hpp"

namespace xot {

static int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

static bool read_exact(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

static bool write_exact(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class Server {
 public:
  Server(std::string socket_path, int n_threads, int max_sessions)
      : socket_path_(std::move(socket_path)),
        pool_(n_threads),
        max_sessions_(max_sessions) {}

  int run() {
    ::unlink(socket_path_.c_str());
    listen_fd_ = ::socket(AF_UNIX, SOCK_STREAM, 0);
    if (listen_fd_ < 0) {
      perror("socket");
      return 1;
    }
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    std::strncpy(addr.sun_path, socket_path_.c_str(), sizeof(addr.sun_path) - 1);
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
      perror("bind");
      return 1;
    }
    if (::listen(listen_fd_, 16) != 0) {
      perror("listen");
      return 1;
    }
    fprintf(stderr, "xot-sidecar: listening on %s (%d compute threads)\n",
            socket_path_.c_str(), pool_.size());
    fflush(stderr);

    while (!quit_) {
      int client = ::accept(listen_fd_, nullptr, nullptr);
      if (client < 0) {
        if (quit_) break;
        continue;
      }
      serve_client(client);
      ::close(client);
    }
    ::close(listen_fd_);
    ::unlink(socket_path_.c_str());
    return 0;
  }

 private:
  void serve_client(int fd) {
    while (!quit_) {
      uint32_t be_len = 0;
      if (!read_exact(fd, &be_len, 4)) return;
      uint32_t header_len = ntohl(be_len);
      if (header_len > (1u << 26)) return;  // 64 MB header cap
      std::string header(header_len, '\0');
      if (!read_exact(fd, header.data(), header_len)) return;

      JsonPtr req;
      try {
        req = JsonParser::parse(header);
      } catch (const std::exception& e) {
        send_error(fd, std::string("bad header: ") + e.what());
        return;
      }

      std::string cmd = req->str("cmd", "");
      std::vector<uint8_t> payload;
      if (req->has("input")) {
        size_t nbytes = static_cast<size_t>(req->at("input")->integer("nbytes", 0));
        payload.resize(nbytes);
        if (nbytes > 0 && !read_exact(fd, payload.data(), nbytes)) return;
      }

      try {
        if (cmd == "ping") {
          auto resp = Json::make(Json::Type::Object);
          resp->set("status", Json::of(std::string("ok")));
          resp->set("loaded", Json::of(model_ != nullptr));
          send_response(fd, resp, nullptr, 0);
        } else if (cmd == "load") {
          handle_load(fd, req);
        } else if (cmd == "infer") {
          handle_infer(fd, req, payload);
        } else if (cmd == "reset") {
          sessions_.erase(req->str("session_id", ""));
          auto resp = Json::make(Json::Type::Object);
          resp->set("status", Json::of(std::string("ok")));
          send_response(fd, resp, nullptr, 0);
        } else if (cmd == "quit") {
          auto resp = Json::make(Json::Type::Object);
          resp->set("status", Json::of(std::string("ok")));
          send_response(fd, resp, nullptr, 0);
          quit_ = true;
          return;
        } else {
          send_error(fd, "unknown cmd: " + cmd);
        }
      } catch (const std::exception& e) {
        send_error(fd, e.what());
      }
    }
  }

  void handle_load(int fd, const JsonPtr& req) {
    std::string model_path = req->str("model_path", "");
    int64_t start = req->integer("layer_start", 0);
    int64_t end = req->integer("layer_end", 0);
    int64_t cache_len = req->integer("cache_len", 2048);
    int64_t t0 = now_ns();
    model_ = std::make_unique<ShardModel>(model_path, start, end, cache_len, &pool_);
    sessions_.clear();
    auto resp = Json::make(Json::Type::Object);
    resp->set("status", Json::of(std::string("ok")));
    resp->set("family", Json::of(model_->config().family));
    resp->set("vocab_size", Json::of(model_->config().vocab_size));
    resp->set("hidden_size", Json::of(model_->config().hidden_size));
    resp->set("is_first", Json::of(model_->is_first()));
    resp->set("is_last", Json::of(model_->is_last()));
    resp->set("cache_len", Json::of(model_->cache_len()));
    resp->set("load_ns", Json::of(now_ns() - t0));
    send_response(fd, resp, nullptr, 0);
  }

  void handle_infer(int fd, const JsonPtr& req, const std::vector<uint8_t>& payload) {
    if (!model_) throw std::runtime_error("no model loaded");
    std::string session_id = req->str("session_id", "default");
    auto input = req->at("input");
    std::string dtype = input->str("dtype", "float32");
    std::vector<int64_t> shape;
    for (auto& d : input->at("shape")->arr_v) shape.push_back(static_cast<int64_t>(d->num_v));

    auto it = sessions_.find(session_id);
    if (it == sessions_.end()) {
      if (static_cast<int>(sessions_.size()) >= max_sessions_) evict_lru();
      it = sessions_.emplace(session_id, model_->new_session()).first;
    }
    Session& sess = it->second;
    sess.last_used_ns = now_ns();

    int64_t t0 = now_ns();
    std::vector<float> out;
    int64_t T;
    if (dtype == "int32") {
      // [B=1, T] token ids — first-shard path (2-D dispatch parity:
      // sharded_inference_engine.py:254-263).
      if (shape.size() != 2 || shape[0] != 1) throw std::runtime_error("expected token shape [1, T]");
      T = shape[1];
      std::vector<int32_t> tokens(static_cast<size_t>(T));
      std::memcpy(tokens.data(), payload.data(), static_cast<size_t>(T) * 4);
      out = model_->forward_tokens(sess, tokens);
    } else {
      // [B=1, T, H] hidden state from the previous ring partition.
      if (shape.size() != 3 || shape[0] != 1) throw std::runtime_error("expected hidden shape [1, T, H]");
      T = shape[1];
      int64_t H = shape[2];
      if (H != model_->config().hidden_size) throw std::runtime_error("hidden dim mismatch");
      std::vector<float> x(static_cast<size_t>(T * H));
      if (dtype == "float32") {
        std::memcpy(x.data(), payload.data(), x.size() * 4);
      } else if (dtype == "bfloat16") {
        const uint16_t* src = reinterpret_cast<const uint16_t*>(payload.data());
        for (size_t i = 0; i < x.size(); ++i) x[i] = bf16_to_f32(src[i]);
      } else {
        throw std::runtime_error("unsupported input dtype " + dtype);
      }
      out = model_->forward_hidden(sess, std::move(x), T);
    }

    int64_t out_dim = model_->is_last() ? model_->config().vocab_size : model_->config().hidden_size;
    auto resp = Json::make(Json::Type::Object);
    resp->set("status", Json::of(std::string("ok")));
    resp->set("pos", Json::of(sess.pos));
    resp->set("elapsed_ns", Json::of(now_ns() - t0));
    auto out_meta = Json::make(Json::Type::Object);
    auto out_shape = Json::make(Json::Type::Array);
    out_shape->arr_v = {Json::of(static_cast<int64_t>(1)), Json::of(T), Json::of(out_dim)};
    out_meta->set("shape", out_shape);

    if (model_->is_last()) {
      // Logits go back fp32 (sampling wants full precision).
      out_meta->set("dtype", Json::of(std::string("float32")));
      out_meta->set("nbytes", Json::of(static_cast<int64_t>(out.size() * 4)));
      resp->set("output", out_meta);
      send_response(fd, resp, out.data(), out.size() * 4);
    } else {
      // Hidden states go back bf16 — the wire stays bf16-clean end to end.
      std::vector<uint16_t> bf(out.size());
      for (size_t i = 0; i < out.size(); ++i) bf[i] = f32_to_bf16(out[i]);
      out_meta->set("dtype", Json::of(std::string("bfloat16")));
      out_meta->set("nbytes", Json::of(static_cast<int64_t>(bf.size() * 2)));
      resp->set("output", out_meta);
      send_response(fd, resp, bf.data(), bf.size() * 2);
    }
  }

  void evict_lru() {
    auto victim = sessions_.begin();
    for (auto it = sessions_.begin(); it != sessions_.end(); ++it)
      if (it->second.last_used_ns < victim->second.last_used_ns) victim = it;
    if (victim != sessions_.end()) sessions_.erase(victim);
  }

  void send_response(int fd, const JsonPtr& resp, const void* payload, size_t payload_bytes) {
    std::string header = resp->dump();
    uint32_t be_len = htonl(static_cast<uint32_t>(header.size()));
    write_exact(fd, &be_len, 4);
    write_exact(fd, header.data(), header.size());
    if (payload_bytes > 0) write_exact(fd, payload, payload_bytes);
  }

  void send_error(int fd, const std::string& message) {
    auto resp = Json::make(Json::Type::Object);
    resp->set("status", Json::of(std::string("error")));
    resp->set("error", Json::of(message));
    send_response(fd, resp, nullptr, 0);
  }

  std::string socket_path_;
  ThreadPool pool_;
  int max_sessions_;
  int listen_fd_ = -1;
  bool quit_ = false;
  std::unique_ptr<ShardModel> model_;
  std::map<std::string, Session> sessions_;
};

}  // namespace xot

int main(int argc, char** argv) {
  std::string socket_path = "/tmp/xot_sidecar.sock";
  int n_threads = static_cast<int>(std::thread::hardware_concurrency());
  int max_sessions = 8;
  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    if (arg == "--socket" && i + 1 < argc) socket_path = argv[++i];
    else if (arg == "--threads" && i + 1 < argc) n_threads = std::atoi(argv[++i]);
    else if (arg == "--max-sessions" && i + 1 < argc) max_sessions = std::atoi(argv[++i]);
    else if (arg == "--help") {
      printf("usage: xot-sidecar [--socket PATH] [--threads N] [--max-sessions N]\n");
      return 0;
    }
  }
  signal(SIGPIPE, SIG_IGN);  // client disconnects must not kill the service
  xot::Server server(socket_path, n_threads, max_sessions);
  return server.run();
}
