#!/usr/bin/env bash
# Editable install into a venv (parity: /root/reference/install.sh:1-11).
set -e

PY=python3
if command -v python3.12 &>/dev/null; then
  PY=python3.12
else
  echo "Python 3.12 recommended; proceeding with $($PY --version)"
fi

$PY -m venv .venv
source .venv/bin/activate
pip install -e .
echo "Installed. Run 'source .venv/bin/activate' then 'xot --help'."
