# Editable install into a venv on Windows (parity: /root/reference/install.ps1).
# TPU serving is a Linux/Cloud story; a Windows peer still joins mixed dev
# rings as a CPU (or CUDA, if a local jax[cuda] wheel is present) node.
$ErrorActionPreference = "Stop"

$py = "python"
try {
  $ver = & $py --version 2>&1
  Write-Host "Using $ver"
} catch {
  Write-Error "Python not found on PATH. Install Python 3.10+ first."
  exit 1
}

& $py -m venv .venv
& .\.venv\Scripts\Activate.ps1
pip install -e .

Write-Host "Installed. Run '.\.venv\Scripts\Activate.ps1' then 'xot --help'."
