"""lock-discipline checker: what happens while a lock is held, and in what
order locks nest.

The runtime's locks guard host-side metadata (logprob store, host KV tier,
flight ring, cost-model rows) that BOTH the engine executor thread and the
event loop touch — so the rules are strict:

- `callback-under-lock`: invoking a user/observer callback while holding a
  lock hands YOUR lock to arbitrary code (PR 6's HostKVStore rule: fire
  `observer` outside the lock). Re-entry or a slow observer deadlocks or
  stalls every other thread on the lock.
- `blocking-under-lock`: sleeps, subprocess, sync HTTP under a lock turn
  every contender into a convoy.
- `device-op-under-lock`: a jax dispatch / host-device transfer under a
  host lock serializes device work behind metadata bookkeeping (the
  /metrics reader should never wait on an HBM copy).
- `await-under-lock`: `await` while holding a THREADING lock parks the
  loop with the lock taken (async-safety flags the lexical case; this one
  rides the same walk for sync defs called from executors).
- `lock-order`: two locks acquired in both orders on some pair of paths —
  the textbook deadlock. Acquisition pairs are collected per function
  (nested `with`) AND through the callgraph (holding L while calling a
  function whose closure acquires M), cycle-tolerantly.

Lock identity is `Class.attr` / `module-var` via the same name heuristic
async-safety uses (`lock`/`mutex`/`cond`/`sema` in the attribute tail).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.xotlint.core import Finding, Repo, dotted_name
from tools.xotlint.callgraph import program
from tools.xotlint.async_safety import _BLOCKING_CALLS, _is_lock_expr

CHECKER = "lock-discipline"

_CALLBACK_TAILS = {"observer", "callback", "cb", "hook", "on_evict", "listener"}
_DEVICE_HEADS = {"jnp", "jax"}
_DEVICE_ATTRS = {"block_until_ready", "device_get", "device_put"}
# jnp.asarray of host metadata is not a dispatch; jax.profiler.* is session
# control whose lock exists precisely to serialize it.
_DEVICE_EXEMPT = {"asarray"}


def _lock_id(sf, node: ast.AST) -> Optional[str]:
  """Stable identity for a lock expression: `self._lock` inside class C ->
  `C._lock`; module-level `_profiling_lock` -> `mod._profiling_lock`."""
  name = dotted_name(node)
  if not name and isinstance(node, ast.Call):
    name = dotted_name(node.func)
  if not name:
    return None
  parts = name.split(".")
  if parts[0] == "self":
    cls = sf.class_scope(node) or "?"
    return f"{cls}.{'.'.join(parts[1:])}"
  return f"{sf.relpath.rsplit('/', 1)[-1]}:{name}"


class _FuncLocks:
  """Per-function lock facts: direct acquisitions, ordered nesting pairs,
  and (lock-held -> calls made) for interprocedural closure."""

  def __init__(self):
    self.acquires: Set[str] = set()
    self.pairs: List[Tuple[str, str, int]] = []       # (outer, inner, line)
    self.calls_under: List[Tuple[str, str, int]] = [] # (lock, callee qual, line)
    self.events: List[Tuple[str, str, str, int]] = [] # (code, lock, what, line)


def _scan_function(prog, info) -> _FuncLocks:
  out = _FuncLocks()
  sf = info.sf

  def visit(node: ast.AST, held_sync: Tuple[str, ...],
            held_all: Tuple[str, ...]) -> None:
    if isinstance(node, (ast.With, ast.AsyncWith)):
      # `async with` means an ASYNCIO lock: awaiting under it is its whole
      # point, and blocking under it is async-safety's beat — so it never
      # extends the SYNC held set the under-lock event checks use. It DOES
      # participate in order analysis (two asyncio locks taken in both
      # orders deadlock just the same).
      new_locks = []
      for item in node.items:
        if _is_lock_expr(item.context_expr):
          lid = _lock_id(sf, item.context_expr)
          if lid is not None:
            new_locks.append(lid)
      for lid in new_locks:
        out.acquires.add(lid)
        for outer in held_all:
          out.pairs.append((outer, lid, node.lineno))
      held_all = held_all + tuple(new_locks)
      if isinstance(node, ast.With):
        held_sync = held_sync + tuple(new_locks)
      for child in node.body:
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
          continue  # nested defs run when called, not here
        visit(child, held_sync, held_all)
      return
    if held_sync and isinstance(node, ast.Await):
      out.events.append(("await-under-lock", held_sync[-1], "await", node.lineno))
    if isinstance(node, ast.Call):
      d = dotted_name(node.func)
      tail = d.rsplit(".", 1)[-1] if d else (
        node.func.attr if isinstance(node.func, ast.Attribute) else "")
      if held_sync and tail in _CALLBACK_TAILS:
        out.events.append(("callback-under-lock", held_sync[-1], tail, node.lineno))
      elif held_sync and d in _BLOCKING_CALLS:
        out.events.append(("blocking-under-lock", held_sync[-1], d, node.lineno))
      elif held_sync and (
          (d.split(".", 1)[0] in _DEVICE_HEADS and tail not in _DEVICE_EXEMPT)
          or tail in _DEVICE_ATTRS) and not d.startswith("jax.profiler."):
        out.events.append(("device-op-under-lock", held_sync[-1], d or tail, node.lineno))
      elif held_all:
        q = prog._resolve_name(info, d)
        if q is not None:
          out.calls_under.append((held_all[-1], q, node.lineno))
    for child in ast.iter_child_nodes(node):
      # Nested defs are separate functions (own facts entry): their bodies
      # run when CALLED, not here — the call is what we record.
      if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        continue
      visit(child, held_sync, held_all)

  for child in ast.iter_child_nodes(info.node):
    if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
      visit(child, (), ())
  return out


def _transitive_acquires(facts: Dict[str, _FuncLocks],
                         prog) -> Dict[str, Set[str]]:
  """lock set each function may acquire, including through callees
  (cycle-tolerant fixpoint)."""
  acq = {q: set(f.acquires) for q, f in facts.items()}
  changed = True
  while changed:
    changed = False
    for q, f in facts.items():
      info = prog.funcs.get(q)
      if info is None:
        continue
      for callee in info.edges:
        extra = acq.get(callee)
        if extra and not extra <= acq[q]:
          acq[q] |= extra
          changed = True
  return acq


def check(repo: Repo) -> List[Finding]:
  prog = program(repo)
  facts: Dict[str, _FuncLocks] = {}
  for q, info in prog.funcs.items():
    if info.sf.tree is not None:
      facts[q] = _scan_function(prog, info)

  findings: List[Finding] = []
  for q, f in facts.items():
    info = prog.funcs[q]
    sf = info.sf
    for code, lock, what, line in f.events:
      if sf.suppressed(line, CHECKER):
        continue
      scope = q.split("::", 1)[1]
      messages = {
        "callback-under-lock": f"`{what}(...)` invoked while holding `{lock}` "
                               "— arbitrary observer code runs under YOUR lock "
                               "(re-entry deadlocks); snapshot under the lock, "
                               "fire outside it",
        "blocking-under-lock": f"blocking `{what}` while holding `{lock}` — "
                               "every contender convoys behind it",
        "device-op-under-lock": f"device op `{what}` while holding `{lock}` — "
                                "metadata readers wait on a device "
                                "dispatch/transfer; move it outside the lock",
        "await-under-lock": f"`await` while holding threading lock `{lock}` — "
                            "the loop parks with the lock taken",
      }
      findings.append(Finding(
        checker=CHECKER, code=code, path=sf.relpath, line=line,
        key=f"{scope}:{lock}:{what}", message=messages[code],
      ))

  # Interprocedural order pairs: direct nesting + (held lock, transitive
  # acquisitions of the callee).
  acq = _transitive_acquires(facts, prog)
  pair_sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
  for q, f in facts.items():
    relpath = prog.funcs[q].sf.relpath
    for outer, inner, line in f.pairs:
      if outer != inner:
        pair_sites.setdefault((outer, inner), (relpath, line))
    for held, callee, line in f.calls_under:
      for inner in acq.get(callee, ()):
        if inner != held:
          pair_sites.setdefault((held, inner), (relpath, line))

  reported: Set[frozenset] = set()
  for (a, b), (relpath, line) in sorted(pair_sites.items()):
    if (b, a) not in pair_sites:
      continue
    key = frozenset((a, b))
    if key in reported:
      continue
    reported.add(key)
    sf = prog.repo.file(relpath)
    if sf is not None and sf.suppressed(line, CHECKER):
      continue
    other_rel, other_line = pair_sites[(b, a)]
    findings.append(Finding(
      checker=CHECKER, code="lock-order", path=relpath, line=line,
      key="<->".join(sorted((a, b))),
      message=f"inconsistent lock order: `{a}` then `{b}` here, but "
              f"`{b}` then `{a}` at {other_rel}:{other_line} — a deadlock "
              "under concurrency; pick one order",
    ))
  return findings
