"""knob-registry checker: every `XOT_*` env read must be a registered knob.

Reads are found in three shapes: `os.getenv("XOT_X", ...)`,
`os.environ.get("XOT_X", ...)` / `os.environ["XOT_X"]` (load context), and
the typed accessors (`knobs.get_int("XOT_X")`, `raw("XOT_X")`, ...). A name
absent from `xotorch_tpu/utils/knobs.py` is either a typo or an
undocumented knob — both fail. Env *writes* (`os.environ["XOT_X"] = ...`)
are not reads and pass.

Two codes:

- `unregistered-knob`: the read names a knob the registry doesn't know.
- `direct-env-read`: a registered knob read via bare `os.getenv` /
  `os.environ` outside the registry module itself — route it through the
  typed accessors so defaults and parsing live in exactly one place.
"""
from __future__ import annotations

import ast
import re
from typing import List

from tools.xotlint.core import Finding, Repo, dotted_name, str_arg

CHECKER = "knob-registry"

_KNOB_RE = re.compile(r"^XOT_[A-Z0-9_]+$")
_ACCESSORS = {"get_int", "get_float", "get_bool", "get_str", "raw"}


def _registered_names(repo: Repo) -> set:
  return set(repo.knobs_module().REGISTRY)


def check(repo: Repo) -> List[Finding]:
  registered = _registered_names(repo)
  findings: List[Finding] = []
  for sf in repo.files():
    if sf.tree is None or sf.relpath == repo.knobs_path:
      continue
    for node in sf.nodes():
      name = None
      direct = False
      if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn in ("os.getenv", "os.environ.get", "environ.get", "getenv"):
          name, direct = str_arg(node), True
        elif fn.rsplit(".", 1)[-1] in _ACCESSORS and (
            "knobs" in fn or fn in _ACCESSORS):
          name = str_arg(node)
      elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if dotted_name(node.value) in ("os.environ", "environ"):
          sub = node.slice
          if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            name, direct = sub.value, True
      if name is None or not _KNOB_RE.match(name):
        continue
      # suppressed() is consulted only once a violation is ESTABLISHED:
      # its hit-recording side effect feeds the stale-suppression audit,
      # so querying it for clean lines would mark dead comments as earned.
      if name not in registered:
        if sf.suppressed(node.lineno, CHECKER):
          continue
        findings.append(Finding(
          checker=CHECKER, code="unregistered-knob", path=sf.relpath,
          line=node.lineno, key=name,
          message=f"`{name}` is read here but not registered in {repo.knobs_path} "
                  "— register it (typo'd knobs silently serve defaults forever)",
        ))
      elif direct:
        if sf.suppressed(node.lineno, CHECKER):
          continue
        findings.append(Finding(
          checker=CHECKER, code="direct-env-read", path=sf.relpath,
          line=node.lineno, key=name,
          message=f"direct env read of `{name}` — use the typed accessors in "
                  f"{repo.knobs_path} (xotorch_tpu.utils.knobs) instead",
        ))
  return findings
