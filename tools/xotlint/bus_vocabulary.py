"""bus-vocabulary checker: the status-bus `"type"` vocabulary is closed.

The opaque-status bus is stringly-typed gossip: producers call
`broadcast_opaque_status(rid, json.dumps({"type": ..., ...}))`, and the
single dispatch handler registered via `.register(...).on_next(...)`
string-compares `status.get("type")`. Nothing ties the two vocabularies
together — a renamed type silently drops every broadcast on the floor
(the same drift class flight's closed EVENTS list exists to stop). From
the shared wire model (wire.py):

- **unheard-type**: a broadcast `"type"` literal no dispatch arm matches —
  every one of those messages is paid for on the wire and then ignored.
- **phantom-arm**: a dispatch arm for a `"type"` nothing broadcasts — dead
  dispatch code, or the producer was renamed out from under it.

Discovery is registration-based: only the handler actually wired to the
bus contributes arms, so unrelated `.get("type")` dispatch tables (UDP
discovery) never pollute the vocabulary.
"""
from __future__ import annotations

from typing import Dict, List

from tools.xotlint.core import Finding, Repo
from tools.xotlint.wire import BusSite, wire_model

CHECKER = "bus-vocabulary"


def check(repo: Repo) -> List[Finding]:
  wm = wire_model(repo)
  if not wm.bus_producers and not wm.bus_arms:
    return []
  produced: Dict[str, BusSite] = {}
  for site in wm.bus_producers:
    produced.setdefault(site.type_, site)
  heard: Dict[str, BusSite] = {}
  for site in wm.bus_arms:
    heard.setdefault(site.type_, site)

  findings: List[Finding] = []
  seen: set = set()
  for type_, site in sorted(produced.items()):
    if type_ in heard:
      continue
    f = Finding(
      CHECKER, "unheard-type", site.sf.relpath, site.line, key=type_,
      message=f"status-bus type `{type_}` is broadcast but no dispatch arm "
              "handles it — every such message is ignored on arrival; add "
              "an arm or delete the producer",
    )
    if f.identity not in seen and not site.sf.suppressed(site.line, CHECKER):
      seen.add(f.identity)
      findings.append(f)
  for type_, site in sorted(heard.items()):
    if type_ in produced:
      continue
    f = Finding(
      CHECKER, "phantom-arm", site.sf.relpath, site.line, key=type_,
      message=f"dispatch arm for status-bus type `{type_}` but nothing "
              "broadcasts it — dead dispatch code, or the producer was "
              "renamed out from under it",
    )
    if f.identity not in seen and not site.sf.suppressed(site.line, CHECKER):
      seen.add(f.identity)
      findings.append(f)
  return findings
