"""doc-drift checker: the README knob reference must match the registry.

The README section between the `BEGIN/END XOT KNOBS` markers is generated
(`python -m tools.xotlint --knob-docs`); this checker re-renders the table
from the live registry and compares per knob, so a knob added, removed,
re-defaulted, or re-documented in code without regenerating the README
fails CI with a per-knob message instead of a wall of diff.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from tools.xotlint.core import Finding, Repo

CHECKER = "doc-drift"

BEGIN_MARK = "<!-- BEGIN XOT KNOBS (generated: python -m tools.xotlint --knob-docs) -->"
END_MARK = "<!-- END XOT KNOBS -->"

_ROW_RE = re.compile(r"^\|\s*`(XOT_[A-Z0-9_]+)`\s*\|\s*(\S+)\s*\|\s*(.*?)\s*\|\s*(.*?)\s*\|$")


def generated_section(repo: Repo) -> str:
  """The full replacement text between (and including) the markers."""
  table = repo.knobs_module().knob_table_markdown()
  return f"{BEGIN_MARK}\n\n{table}\n{END_MARK}"


def _parse_rows(section: str) -> Dict[str, Tuple[str, str, str]]:
  rows: Dict[str, Tuple[str, str, str]] = {}
  for line in section.splitlines():
    m = _ROW_RE.match(line.strip())
    if m:
      rows[m.group(1)] = (m.group(2), m.group(3), m.group(4))
  return rows


def _find_section(text: str) -> Optional[str]:
  start = text.find(BEGIN_MARK)
  end = text.find(END_MARK)
  if start < 0 or end < 0 or end < start:
    return None
  return text[start:end + len(END_MARK)]


def check(repo: Repo) -> List[Finding]:
  readme = repo.read_text(repo.readme_path)
  if readme is None:
    return [Finding(CHECKER, "missing-readme", repo.readme_path, 1,
                    f"{repo.readme_path} not found", key="readme")]
  section = _find_section(readme)
  if section is None:
    return [Finding(
      CHECKER, "missing-section", repo.readme_path, 1,
      f"{repo.readme_path} has no `{BEGIN_MARK}` ... `{END_MARK}` block — "
      "add one and fill it with `python -m tools.xotlint --knob-docs`",
      key="section",
    )]
  documented = _parse_rows(section)
  expected = _parse_rows(generated_section(repo))
  findings: List[Finding] = []
  line_of = {name: i + 1 for i, line in enumerate(readme.splitlines())
             for name in documented if f"`{name}`" in line}
  for name, row in expected.items():
    if name not in documented:
      findings.append(Finding(
        CHECKER, "undocumented-knob", repo.readme_path, 1, key=name,
        message=f"`{name}` is registered but missing from the README knob table "
                "— regenerate with `python -m tools.xotlint --knob-docs`",
      ))
    elif documented[name] != row:
      findings.append(Finding(
        CHECKER, "stale-doc", repo.readme_path, line_of.get(name, 1), key=name,
        message=f"`{name}` README row (type/default/doc) differs from the registry "
                "— regenerate with `python -m tools.xotlint --knob-docs`",
      ))
  for name in documented:
    if name not in expected:
      findings.append(Finding(
        CHECKER, "unknown-documented-knob", repo.readme_path,
        line_of.get(name, 1), key=name,
        message=f"README documents `{name}` but the registry has no such knob "
                "— remove the row or register the knob",
      ))
  return findings
