"""CLI: `python -m tools.xotlint` — run all checkers, compare to baseline.

Exit codes: 0 = no non-baselined findings, 1 = findings, 2 = usage/config
error. `--knob-docs` / `--endpoint-docs` print the generated README sections and
exit. `--wire-info` prints the non-gating wire-schema observations.
`--stats` prints per-checker wall time + finding counts; `--stats-file`
writes them as JSON (the CI artifact guarding the shared-AST-cache perf).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

from tools.xotlint import CHECKERS, run_checkers
from tools.xotlint import doc_drift, endpoint_contract, wire_schema
from tools.xotlint.core import Repo, load_baseline, write_baseline

DEFAULT_BASELINE = os.path.join("tools", "xotlint", "baseline.json")


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(
    prog="python -m tools.xotlint",
    description="Repo-native static analysis, thirteen checkers: async-safety, "
                "knob registry, doc drift, metrics consistency, exception "
                "hygiene, the callgraph-driven hotpath-sync, retrace-hazard, "
                "donation-safety and lock-discipline, plus the wire-contract "
                "endpoint-contract, wire-schema, bus-vocabulary and "
                "http-client-hygiene.",
  )
  parser.add_argument("--root", default=".", help="repo root (default: cwd)")
  parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                      help="baseline file of grandfathered findings")
  parser.add_argument("--write-baseline", action="store_true",
                      help="write the current findings as the new baseline and exit")
  parser.add_argument("--no-baseline", action="store_true",
                      help="ignore the baseline (report every finding)")
  parser.add_argument("--knob-docs", action="store_true",
                      help="print the generated README knob-reference section and exit")
  parser.add_argument("--endpoint-docs", action="store_true",
                      help="print the generated README HTTP-API section and exit")
  parser.add_argument("--wire-info", action="store_true",
                      help="print non-gating wire-schema observations and exit")
  parser.add_argument("--checker", action="append", default=None,
                      help="run only this checker (repeatable)")
  parser.add_argument("--stats", action="store_true",
                      help="print per-checker wall time and finding counts")
  parser.add_argument("--stats-file", default=None,
                      help="write per-checker stats as JSON (CI artifact)")
  args = parser.parse_args(argv)

  repo = Repo(args.root)
  if args.knob_docs:
    print(doc_drift.generated_section(repo))
    return 0
  if args.endpoint_docs:
    print(endpoint_contract.generated_section(repo))
    return 0
  if args.wire_info:
    for f in wire_schema.info(repo):
      print(f.render())
    return 0

  unknown = [c for c in (args.checker or []) if c not in CHECKERS]
  if unknown:
    # A typo'd name silently running zero checkers would read as "clean".
    print(f"unknown checker(s): {', '.join(unknown)} "
          f"(available: {', '.join(CHECKERS)})", file=sys.stderr)
    return 2

  stats: dict = {}
  t_total = time.monotonic()
  findings = run_checkers(repo, only=args.checker, stats=stats)
  total_secs = round(time.monotonic() - t_total, 4)
  if args.stats or args.stats_file:
    payload = {"total_secs": total_secs, "checkers": stats}
    if args.stats:
      width = max(len(n) for n in stats) if stats else 10
      for name, row in stats.items():
        print(f"{name:<{width}}  {row['secs']:8.4f}s  {row['findings']:3d} finding(s)",
              file=sys.stderr)
      print(f"{'total':<{width}}  {total_secs:8.4f}s", file=sys.stderr)
    if args.stats_file:
      with open(args.stats_file, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")

  baseline_path = os.path.join(args.root, args.baseline)
  if args.write_baseline:
    write_baseline(baseline_path, findings)
    print(f"wrote {len(findings)} finding(s) to {args.baseline}")
    return 0

  baseline = set() if args.no_baseline else set(load_baseline(baseline_path))
  fresh = [f for f in findings if f.identity not in baseline]
  stale = baseline - {f.identity for f in findings}

  for f in fresh:
    print(f.render())
  if stale:
    print(f"note: {len(stale)} baseline entr{'y is' if len(stale) == 1 else 'ies are'} "
          "stale (finding fixed — remove from baseline):", file=sys.stderr)
    for identity in sorted(stale):
      print(f"  {identity}", file=sys.stderr)
  if fresh:
    print(f"\nxotlint: {len(fresh)} finding(s) "
          f"({len(findings) - len(fresh)} baselined)", file=sys.stderr)
    return 1
  print(f"xotlint: clean ({len(findings)} baselined finding(s))"
        if findings else "xotlint: clean")
  return 0


if __name__ == "__main__":
  sys.exit(main())
