"""CLI: `python -m tools.xotlint` — run all checkers, compare to baseline.

Exit codes: 0 = no non-baselined findings, 1 = findings, 2 = usage/config
error. `--knob-docs` prints the generated README knob section and exits.
"""
from __future__ import annotations

import argparse
import os
import sys

from tools.xotlint import CHECKERS, run_checkers
from tools.xotlint import doc_drift
from tools.xotlint.core import Repo, load_baseline, write_baseline

DEFAULT_BASELINE = os.path.join("tools", "xotlint", "baseline.json")


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(
    prog="python -m tools.xotlint",
    description="Repo-native static analysis: async-safety, knob registry, "
                "doc drift, metrics consistency, exception hygiene.",
  )
  parser.add_argument("--root", default=".", help="repo root (default: cwd)")
  parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                      help="baseline file of grandfathered findings")
  parser.add_argument("--write-baseline", action="store_true",
                      help="write the current findings as the new baseline and exit")
  parser.add_argument("--no-baseline", action="store_true",
                      help="ignore the baseline (report every finding)")
  parser.add_argument("--knob-docs", action="store_true",
                      help="print the generated README knob-reference section and exit")
  parser.add_argument("--checker", action="append", default=None,
                      help="run only this checker (repeatable)")
  args = parser.parse_args(argv)

  repo = Repo(args.root)
  if args.knob_docs:
    print(doc_drift.generated_section(repo))
    return 0

  unknown = [c for c in (args.checker or []) if c not in CHECKERS]
  if unknown:
    # A typo'd name silently running zero checkers would read as "clean".
    print(f"unknown checker(s): {', '.join(unknown)} "
          f"(available: {', '.join(CHECKERS)})", file=sys.stderr)
    return 2

  findings = run_checkers(repo, only=args.checker)

  baseline_path = os.path.join(args.root, args.baseline)
  if args.write_baseline:
    write_baseline(baseline_path, findings)
    print(f"wrote {len(findings)} finding(s) to {args.baseline}")
    return 0

  baseline = set() if args.no_baseline else set(load_baseline(baseline_path))
  fresh = [f for f in findings if f.identity not in baseline]
  stale = baseline - {f.identity for f in findings}

  for f in fresh:
    print(f.render())
  if stale:
    print(f"note: {len(stale)} baseline entr{'y is' if len(stale) == 1 else 'ies are'} "
          "stale (finding fixed — remove from baseline):", file=sys.stderr)
    for identity in sorted(stale):
      print(f"  {identity}", file=sys.stderr)
  if fresh:
    print(f"\nxotlint: {len(fresh)} finding(s) "
          f"({len(findings) - len(fresh)} baselined)", file=sys.stderr)
    return 1
  print(f"xotlint: clean ({len(findings)} baselined finding(s))"
        if findings else "xotlint: clean")
  return 0


if __name__ == "__main__":
  sys.exit(main())
