"""wire-schema checker: consumed JSON keys must exist on the producing side.

The silent-`.get`-default bug class: `q.get("activ_requests") or 0` steers
the fleet on zeros forever, because a typo'd key read across a process
boundary fails OPEN. From the shared wire model (wire.py):

- **unproduced-key** (error): a key consumed from response JSON (taint
  from `await resp.json()` / `json.loads` under `urlopen` / a fetch
  wrapper, followed through assignments and attribute stores) that NO
  constant dict key in the scanned tree produces. When the consumption's
  route is known, the message names the handler whose reachable closure
  was searched first.
- **unreachable-key** (info, `--wire-info`): the key exists somewhere in
  the tree but not in the matched handler's produced-key closure — worth
  a look, not a gate (closures are over-approximate but still miss
  data-driven producers).
- **unconsumed-key** (info, `--wire-info`): a top-level literal key of a
  `web.json_response({...})` body nothing in the repo reads. Most are the
  OpenAI-compatible surface consumed by external clients, which is
  exactly why this is info, not error.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.xotlint.core import Finding, Repo, dotted_name
from tools.xotlint.wire import wire_model

CHECKER = "wire-schema"

# Consumed keys that are request-body/bookkeeping vocabulary rather than
# response-schema reads are still checked — they must simply exist as a
# produced key somewhere, which request builders guarantee.


def _closure_for(wm, route_path: str) -> Optional[Set[str]]:
  keys: Optional[Set[str]] = None
  for route in wm.routes:
    if route.handler_qual and route.path == route_path:
      cl = wm.produced_closure(route.handler_qual)
      keys = cl if keys is None else (keys | cl)
  return keys


def check(repo: Repo) -> List[Finding]:
  wm = wire_model(repo)
  findings: List[Finding] = []
  seen: set = set()
  for c in wm.consumptions:
    if c.key in wm.produced_global:
      continue
    where = f" of `{c.route}` responses" if c.route else ""
    f = Finding(
      CHECKER, "unproduced-key", c.sf.relpath, c.line,
      key=f"{c.scope}:{c.key}",
      message=f"`{c.key}` is read from cross-process JSON{where} but no "
              "producer in the tree ever emits that key — a typo'd or stale "
              "read that fails open to its `.get` default",
    )
    if f.identity in seen or c.sf.suppressed(c.line, CHECKER):
      continue
    seen.add(f.identity)
    findings.append(f)
  return findings


def info(repo: Repo) -> List[Finding]:
  """Non-gating wire observations, printed by `--wire-info` only."""
  wm = wire_model(repo)
  out: List[Finding] = []
  seen: set = set()
  consumed_all = {c.key for c in wm.consumptions}

  for c in wm.consumptions:
    if c.route is None or c.key not in wm.produced_global:
      continue
    closure = _closure_for(wm, c.route)
    if closure is None or c.key in closure:
      continue
    f = Finding(
      CHECKER, "unreachable-key", c.sf.relpath, c.line,
      key=f"{c.scope}:{c.key}",
      message=f"`{c.key}` is read from `{c.route}` responses but is not in "
              "the registered handler's produced-key closure — produced "
              "elsewhere in the tree, so likely fine, but worth a look",
    )
    if f.identity not in seen:
      seen.add(f.identity)
      out.append(f)

  # Top-level literal response keys nothing in the repo reads.
  for sf in wm.files:
    for node in sf.nodes():
      if not (isinstance(node, ast.Call)
              and dotted_name(node.func).endswith("json_response")
              and node.args and isinstance(node.args[0], ast.Dict)):
        continue
      for k in node.args[0].keys:
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
          continue
        if k.value in consumed_all:
          continue
        f = Finding(
          CHECKER, "unconsumed-key", sf.relpath, node.lineno,
          key=f"{sf.func_scope(node)}:{k.value}",
          message=f"response key `{k.value}` has no in-repo consumer "
                  "(external clients may still read it — informational)",
        )
        if f.identity not in seen:
          seen.add(f.identity)
          out.append(f)
  return out
