"""xotlint: repo-native static analysis for the xotorch_tpu runtime.

Thirteen checkers, each a module exposing `check(repo) -> list[Finding]`.
Five are per-function (PR 5):

- async-safety        blocking calls / sync locks / raw create_task in async code
- knob-registry       every XOT_* env read routes through utils/knobs.py
- doc-drift           README knob reference matches the registry
- metrics-consistency incremented counters are exported, `_total` convention
- exception-hygiene   no silent `except Exception: pass` on serving paths

Four are whole-program, built on the shared callgraph core (callgraph.py):

- hotpath-sync        no host sync reachable from the dispatch entry points
- retrace-hazard      jit sites keep a bounded executable count
- donation-safety     donated buffers are dead after the call
- lock-discipline     nothing slow/foreign under a lock; consistent order

Four analyze the cross-process wire contracts, built on the shared wire
model (wire.py: routes, client URLs, JSON key flows, bus vocabulary):

- endpoint-contract   client URL+method matches a registered route; no
                      dead routes outside the external-surface allowlist
- wire-schema         a key consumed across a process boundary is produced
                      somewhere (the silent-`.get`-default bug class)
- bus-vocabulary      broadcast status "type"s and dispatch arms agree
- http-client-hygiene every cross-process HTTP call has a timeout and an
                      exception barrier before its entry point

The runner itself audits suppressions (`suppression-audit` findings): an
`# xotlint: disable=<checker>` comment whose checker no longer fires on
that line is stale and must be deleted; one without a parenthesized reason
is incomplete. Run as `python -m tools.xotlint`; see `--help` for baseline
management, `--stats` for per-checker timing, `--knob-docs` /
`--endpoint-docs` for README generation.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

from tools.xotlint.core import Finding, Repo
from tools.xotlint import (  # noqa: E402  (registry of checker modules)
  async_safety,
  bus_vocabulary,
  doc_drift,
  donation_safety,
  endpoint_contract,
  exception_hygiene,
  hotpath_sync,
  http_client_hygiene,
  knob_registry,
  lock_discipline,
  metrics_consistency,
  retrace_hazard,
  wire_schema,
)

CHECKERS = {
  async_safety.CHECKER: async_safety,
  knob_registry.CHECKER: knob_registry,
  doc_drift.CHECKER: doc_drift,
  metrics_consistency.CHECKER: metrics_consistency,
  exception_hygiene.CHECKER: exception_hygiene,
  hotpath_sync.CHECKER: hotpath_sync,
  retrace_hazard.CHECKER: retrace_hazard,
  donation_safety.CHECKER: donation_safety,
  lock_discipline.CHECKER: lock_discipline,
  endpoint_contract.CHECKER: endpoint_contract,
  wire_schema.CHECKER: wire_schema,
  bus_vocabulary.CHECKER: bus_vocabulary,
  http_client_hygiene.CHECKER: http_client_hygiene,
}

AUDIT = "suppression-audit"


def _audit_suppressions(repo: Repo) -> List[Finding]:
  """Runner-level pass (not a registered checker): every inline suppression
  must still be EARNED — its named checker queried that line and would
  have fired. Requires a full run (all checkers), so run_checkers only
  calls this when none were filtered out. Audits every LOADED file — the
  package plus the tool trees the wire model pulled in — so suppressions
  in tools/soak etc. rot-check like package ones."""
  findings: List[Finding] = []
  for sf in repo.loaded_files():
    hits = sf.suppression_hits
    for line, names, has_reason in sf.suppression_sites():
      for name in names:
        if name == "all":
          continue  # blanket disables can't be attributed; reviewed by hand
        if name not in CHECKERS and name != AUDIT:
          findings.append(Finding(
            checker=AUDIT, code="unknown-checker", path=sf.relpath, line=line,
            key=f"{line}:{name}",
            message=f"suppression names unknown checker `{name}` — it disables "
                    "nothing (typo, or the checker was renamed)",
          ))
        elif (line, name) not in hits:
          findings.append(Finding(
            checker=AUDIT, code="stale-suppression", path=sf.relpath, line=line,
            key=f"{sf.func_scope_at_line(line)}:{name}",
            message=f"`xotlint: disable={name}` no longer suppresses anything "
                    "on this line (the finding was fixed or moved) — delete "
                    "the comment so suppressions can't rot",
          ))
      if not has_reason:
        findings.append(Finding(
          checker=AUDIT, code="missing-reason", path=sf.relpath, line=line,
          key=f"{sf.func_scope_at_line(line)}:{','.join(names)}",
          message="suppression without a parenthesized reason — write WHY "
                  "this is safe: `# xotlint: disable=<checker> (reason)`",
        ))
  return findings


def run_checkers(repo: Repo, only: Optional[Sequence[str]] = None,
                 stats: Optional[Dict[str, dict]] = None) -> List[Finding]:
  findings: List[Finding] = []
  for name, module in CHECKERS.items():
    if only and name not in only:
      continue
    t0 = time.monotonic()
    found = module.check(repo)
    if stats is not None:
      stats[name] = {"secs": round(time.monotonic() - t0, 4),
                     "findings": len(found)}
    findings.extend(found)
  if not only:  # the audit needs every checker's suppression hits
    t0 = time.monotonic()
    found = _audit_suppressions(repo)
    if stats is not None:
      stats[AUDIT] = {"secs": round(time.monotonic() - t0, 4),
                      "findings": len(found)}
    findings.extend(found)
  findings.sort(key=lambda f: (f.path, f.line, f.checker, f.code, f.key))
  return findings
