"""xotlint: repo-native static analysis for the xotorch_tpu runtime.

Five checkers, each a module exposing `check(repo) -> list[Finding]`:

- async-safety        blocking calls / sync locks / raw create_task in async code
- knob-registry       every XOT_* env read routes through utils/knobs.py
- doc-drift           README knob reference matches the registry
- metrics-consistency incremented counters are exported, `_total` convention
- exception-hygiene   no silent `except Exception: pass` on serving paths

Run as `python -m tools.xotlint`; see `--help` for baseline management and
`--knob-docs` for README generation.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from tools.xotlint.core import Finding, Repo
from tools.xotlint import (  # noqa: E402  (registry of checker modules)
  async_safety,
  doc_drift,
  exception_hygiene,
  knob_registry,
  metrics_consistency,
)

CHECKERS = {
  async_safety.CHECKER: async_safety,
  knob_registry.CHECKER: knob_registry,
  doc_drift.CHECKER: doc_drift,
  metrics_consistency.CHECKER: metrics_consistency,
  exception_hygiene.CHECKER: exception_hygiene,
}


def run_checkers(repo: Repo, only: Optional[Sequence[str]] = None) -> List[Finding]:
  findings: List[Finding] = []
  for name, module in CHECKERS.items():
    if only and name not in only:
      continue
    findings.extend(module.check(repo))
  findings.sort(key=lambda f: (f.path, f.line, f.checker, f.code, f.key))
  return findings
