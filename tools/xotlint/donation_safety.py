"""donation-safety checker: a donated buffer is DEAD after the call.

`donate_argnums`/`donate_argnames` tells XLA it may alias the input's
memory for the output — the Python-side array is invalidated the moment
the dispatch runs. Reading it afterwards raises on TPU ("donated buffer
was deleted") but often WORKS on the CPU backend tests run on, so the bug
class ships silently. Three findings:

- `use-after-donate`: the caller reads the donated expression after the
  call, before rebinding it (`out = decode_chunk(p, tok, state.cache, ...)`
  then touching `state.cache` before `state.cache = out[...]`).
- `donated-result-discarded`: the call's result is dropped — the donated
  buffer is gone and nothing replaced it (the arena vanishes).

Donated callables are found three ways (callgraph jit-site table):
decorated defs (`@partial(jax.jit, donate_argnames=...)`), jit results
assigned to a name (`forward_jit = jax.jit(fwd, donate_argnums=(2,))` —
call sites matched by attribute tail, the `ctx.forward_jit(...)` idiom),
and factory functions returning a donated jit (`_commit_jit()(arena, ...)`
— the lazy-jit idiom). Wrapper functions that pass their own parameter in
a donated position (paged_cache.commit_pages) donate TRANSITIVELY: their
callers are checked against the wrapper's signature too.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.xotlint.core import Finding, Repo, dotted_name
from tools.xotlint.callgraph import jit_sites, program

CHECKER = "donation-safety"


class _Donated:
  """name -> donated positional indices (and argnames for kw matching)."""

  def __init__(self):
    self.by_name: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {}
    self.factories: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {}

  def add(self, name: str, pos: Tuple[int, ...], names: Tuple[str, ...]) -> None:
    if pos or names:
      old = self.by_name.get(name, ((), ()))
      self.by_name[name] = (tuple(sorted(set(old[0] + pos))),
                            tuple(sorted(set(old[1] + names))))


def _donation_table(repo: Repo) -> _Donated:
  table = _Donated()
  for site in jit_sites(repo):
    if not (site.donate_positions or site.donate_names):
      continue
    table.add(site.name, site.donate_positions, site.donate_names)
    if site.factory is not None:
      scope = site.factory.split("::", 1)[1]
      table.factories[scope.rsplit(".", 1)[-1]] = (
        site.donate_positions, site.donate_names)
  # Transitive wrappers: a function passing its OWN parameter in a donated
  # position donates that parameter to its callers. One propagation round
  # covers the repo's wrapper depth (commit_pages -> _commit_jit()).
  prog = program(repo)
  for info in prog.funcs.values():
    params = [a.arg for a in info.node.args.posonlyargs + info.node.args.args]
    for node in ast.walk(info.node):
      if not isinstance(node, ast.Call):
        continue
      spec = _donated_spec_for_call(node, table)
      if spec is None:
        continue
      pos, names = spec
      donated_params = []
      for i, arg in enumerate(node.args):
        if i in pos and isinstance(arg, ast.Name) and arg.id in params:
          donated_params.append(params.index(arg.id))
      for kw in node.keywords:
        if kw.arg in names and isinstance(kw.value, ast.Name) and kw.value.id in params:
          donated_params.append(params.index(kw.value.id))
      if donated_params:
        table.add(info.node.name, tuple(donated_params), ())
  return table


def _donated_spec_for_call(call: ast.Call,
                           table: _Donated) -> Optional[Tuple[Tuple[int, ...], Tuple[str, ...]]]:
  """The (positions, argnames) donated by this call, if it targets a known
  donated callable: by bare/tail name, or a factory-call-of-call."""
  func = call.func
  if isinstance(func, ast.Call):
    inner = dotted_name(func.func)
    if inner:
      spec = table.factories.get(inner.rsplit(".", 1)[-1])
      if spec is not None:
        return spec
    return None
  d = dotted_name(func)
  if not d:
    return None
  return table.by_name.get(d.rsplit(".", 1)[-1])


def _stmt_of(sf, node: ast.AST) -> Optional[ast.stmt]:
  while node is not None and not isinstance(node, ast.stmt):
    node = sf.parent(node)
  return node


def _following_stmts(sf, stmt: ast.stmt, within: ast.AST) -> List[ast.stmt]:
  """Statements that can execute AFTER `stmt` completes, in order: later
  siblings in its block, then later siblings of each enclosing block up to
  `within`. Sibling BRANCHES of the same if/try never run after the call
  and are excluded (that is the point — a linear lineno scan would read
  the `else:` arm as 'after')."""
  out: List[ast.stmt] = []
  node: ast.AST = stmt
  while node is not None and node is not within:
    parent = sf.parent(node)
    if parent is None:
      break
    for field in ("body", "orelse", "finalbody", "handlers"):
      block = getattr(parent, field, None)
      if isinstance(block, list) and node in block:
        out.extend(block[block.index(node) + 1:])
        break
    node = parent
  return out


def _reads_name(node: ast.AST, name: str) -> bool:
  """Does the expression READ `name` (exact dotted match or a deeper
  access through it)?"""
  for n in ast.walk(node):
    if isinstance(n, (ast.Name, ast.Attribute)) and isinstance(
        getattr(n, "ctx", None), ast.Load):
      d = dotted_name(n)
      if d == name or (d and d.startswith(name + ".")):
        return True
  return False


def _assigns_name(stmt: ast.stmt, name: str) -> bool:
  """Any assignment to `name` within the statement — compound statements
  (if/try) count when ANY arm rebinds (conservative toward no-finding: the
  `if counts: a, d = out / else: d = out` rebind idiom must read as a
  rebind, and a statement that both reads and rebinds is ambiguous in
  order, so the rebind wins)."""
  for node in ast.walk(stmt):
    targets = []
    if isinstance(node, ast.Assign):
      targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
      targets = [node.target]
    for t in targets:
      for leaf in ast.walk(t):
        if isinstance(leaf, (ast.Name, ast.Attribute)) and dotted_name(leaf) == name:
          return True
  return False


def check(repo: Repo) -> List[Finding]:
  table = _donation_table(repo)
  prog = program(repo)
  findings: List[Finding] = []
  for info in prog.funcs.values():
    sf = info.sf
    for node in ast.walk(info.node):
      if not isinstance(node, ast.Call):
        continue
      spec = _donated_spec_for_call(node, table)
      if spec is None:
        continue
      pos, kwnames = spec
      donated_exprs = [node.args[i] for i in pos if i < len(node.args)]
      donated_exprs += [kw.value for kw in node.keywords if kw.arg in kwnames]
      donated = [dotted_name(e) for e in donated_exprs]
      donated = [d for d in donated if d]
      if not donated:
        continue
      stmt = _stmt_of(sf, node)
      if stmt is None:
        continue
      if isinstance(stmt, ast.Return):
        continue  # result escapes; locals die with the frame
      rebound_now = set()
      if isinstance(stmt, ast.Assign):
        for d in donated:
          if any(dotted_name(leaf) == d
                 for t in stmt.targets for leaf in ast.walk(t)
                 if isinstance(leaf, (ast.Name, ast.Attribute))):
            rebound_now.add(d)
      elif isinstance(stmt, ast.Expr) and stmt.value is node:
        if not sf.suppressed(node.lineno, CHECKER):
          findings.append(Finding(
            checker=CHECKER, code="donated-result-discarded", path=sf.relpath,
            line=node.lineno, key=f"{sf.func_scope(node)}:{donated[0]}",
            message=f"result of donating call discarded — `{donated[0]}` was "
                    "donated (its device buffer is invalidated) and nothing "
                    "rebinds it; assign the result back",
          ))
        continue
      for d in donated:
        if d in rebound_now or d == "self" or "." not in d and d in ("_",):
          continue
        # Post-call scan over statements that can actually run after the
        # call (later siblings up the block chain — other branches of the
        # same if/try are excluded): a Load of the donated name before any
        # rebind is a use-after-donate. Loop back-edges are ignored — a
        # donate-then-reuse ACROSS iterations must rebind inside the loop
        # body anyway, which this still checks linearly.
        use_line = rebind_line = None
        for s in _following_stmts(sf, stmt, info.node):
          if rebind_line is None and _assigns_name(s, d):
            rebind_line = s.lineno
          if use_line is None and not _assigns_name(s, d) and _reads_name(s, d):
            use_line = s.lineno
          if rebind_line is not None or use_line is not None:
            break
        if use_line is not None and (rebind_line is None or use_line < rebind_line):
          if sf.suppressed(use_line, CHECKER) or sf.suppressed(node.lineno, CHECKER):
            continue
          findings.append(Finding(
            checker=CHECKER, code="use-after-donate", path=sf.relpath,
            line=use_line, key=f"{sf.func_scope(node)}:{d}",
            message=f"`{d}` is read after being donated at line {node.lineno} "
                    "— the buffer is invalidated by the dispatch (works on "
                    "CPU, raises on TPU); rebind it from the result first",
          ))
  return findings
