"""Wire-model extractor: the repo's cross-process string contracts.

PRs 14-19 moved correctness into strings that cross process boundaries —
HTTP routes, JSON payload keys, status-bus `"type"` literals. This module
builds the ONE shared model of those seams that the four wire checkers
(endpoint-contract, wire-schema, bus-vocabulary, http-client-hygiene)
consume, memoized on the Repo like `callgraph.program`:

- **routes**: every `add_get/add_post/add_delete/add_put` registration in
  the package, including paths bound by a `for path in ("/a", "/b"):`
  loop (router/app.py's proxy fan-in). Handlers resolve to callgraph
  quals so produced-key closures can start from them.
- **client refs**: every URL a client builds — f-strings and string
  concatenation feeding `session.get/post` / `urllib.request.urlopen`,
  plus LOOSE references (a path literal handed to a fetch helper, an
  f-string assigned to a variable). Dynamic segments render as `{x}` and
  match any route segment; query strings are stripped.
- **transports**: the raw HTTP call sites with their timeout/containment
  facts — http-client-hygiene's work list.
- **consumptions**: `.get("k")` / `["k"]` reads on names tainted by a
  response-JSON root (`await resp.json()`, `json.loads(r.read())` under
  `urlopen`, or a call to a local fetch wrapper). Taint follows simple
  assignment, `x or {}`, subscripts, and attribute stores (`rep.queue =
  q.get("admission")` taints `.queue` reads repo-wide — the router ->
  fleet-controller seam).
- **produced keys**: every constant dict key in the scanned tree (the
  global universe a consumed key must exist in), plus per-handler BFS
  closures over the callgraph with a bounded same-method-name fallback
  for calls the import resolver punts on (`gate.compact()` through an
  untyped `self.node`).
- **bus vocabulary**: `"type"` literals in `broadcast_opaque_status`
  payloads vs the dispatch arms of the handler registered via
  `.register(...).on_next(self.<handler>)`.

The scan covers `repo.files()` (the package) plus the CLI tool roots that
speak the node API (tools/anatomy, tools/history, tools/soak) — loaded
through `repo.file()` so they share the AST cache and suppression
bookkeeping but are NOT subjected to the per-function package checkers.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.xotlint.core import Repo, SourceFile, dotted_name, str_arg
from tools.xotlint.callgraph import Program, program

# CLI tool trees scanned for client sites (package checkers skip these).
TOOL_ROOTS = ("tools/anatomy", "tools/history", "tools/soak")

_ROUTE_REG = {"add_get": "GET", "add_post": "POST",
              "add_delete": "DELETE", "add_put": "PUT"}

# A rendered URL path: absolute, segments of name-ish chars or `{param}`.
_PATH_RE = re.compile(r"^/[A-Za-z0-9_{}./-]*$")

# Unresolved-call fallback for produced-key closures: a dotted call the
# import resolver punted on expands to every same-named def in the program
# unless the name is hopelessly generic (dict/list/logging vocabulary) or
# the candidate set is too wide to mean anything.
_FALLBACK_STOP = {
  "get", "items", "keys", "values", "append", "add", "update", "pop",
  "join", "split", "format", "encode", "decode", "strip", "startswith",
  "endswith", "record", "register", "info", "debug", "warning", "error",
  "put", "extend", "copy", "sort", "close", "send", "write", "read",
}
_FALLBACK_MAX_CANDIDATES = 12


@dataclass
class Route:
  """One registered server route."""
  method: str                    # GET/POST/DELETE/PUT
  path: str                      # template, e.g. "/v1/kv/{key}"
  handler: str                   # as written, e.g. "self.handle_get_kv"
  handler_qual: Optional[str]    # resolved callgraph qual, when known
  sf: SourceFile
  line: int


@dataclass
class ClientRef:
  """One client-side reference to a server path (transport arg or loose)."""
  path: str                      # template, query stripped
  method: Optional[str]          # None for loose references
  sf: SourceFile
  line: int
  scope: str
  kind: str                      # "session" | "urllib" | "loose"


@dataclass
class Transport:
  """One raw HTTP call site (http-client-hygiene's unit of work)."""
  kind: str                      # "session" | "urllib"
  method: Optional[str]
  path: Optional[str]            # rendered template, when the URL renders
  sf: SourceFile
  call: ast.Call
  line: int
  scope: str
  has_timeout: bool


@dataclass
class Consumption:
  """One `.get("k")` / `["k"]` read on response-JSON-tainted data."""
  key: str
  route: Optional[str]           # path template the taint came from
  sf: SourceFile
  line: int
  scope: str


@dataclass
class BusSite:
  """One status-bus `"type"` literal (producer or dispatch arm)."""
  type_: str
  sf: SourceFile
  line: int


def _path_of(urlish: str) -> Optional[str]:
  """Rendered URL template -> server path template, or None.

  `http://h:{p}/v1/queue?x=1` -> `/v1/queue`; `{base}/v1/kv/{key}?payload=1`
  -> `/v1/kv/{key}`; a bare `/healthcheck` passes through."""
  s = urlish.split("?", 1)[0]
  if s.startswith(("http://", "https://")):
    rest = s.split("://", 1)[1]
    slash = rest.find("/")
    if slash < 0:
      # `http://host:{port}{path}`: the whole path is a runtime argument —
      # unknown, NOT the root route. A literal slashless URL is "/".
      return None if "{" in rest else "/"
    s = rest[slash:]
  elif not s.startswith("/"):
    # `{base}/v1/anatomy`, `{x}/healthcheck`: drop the host-ish prefix.
    slash = s.find("/")
    if slash < 0 or not s.startswith("{"):
      return None
    s = s[slash:]
  if s != "/" and s.endswith("/"):
    s = s.rstrip("/")
  return s if _PATH_RE.match(s) else None


def path_match(client: str, route: str) -> bool:
  """Template match with `{param}` wildcards on either side."""
  a, b = client.split("/"), route.split("/")
  if len(a) != len(b):
    return False
  return all(x == y or (x.startswith("{") and x.endswith("}"))
             or (y.startswith("{") and y.endswith("}"))
             for x, y in zip(a, b))


def _collect_keys(root: ast.AST) -> Set[str]:
  """Constant JSON-ish keys a subtree can produce: dict literals,
  `dict(k=...)` kwargs, `d["k"] = v` stores, `.setdefault("k", ...)`."""
  keys: Set[str] = set()
  for node in ast.walk(root):
    if isinstance(node, ast.Dict):
      for k in node.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
          keys.add(k.value)
    elif isinstance(node, ast.Call):
      name = dotted_name(node.func)
      if name == "dict":
        keys.update(kw.arg for kw in node.keywords if kw.arg)
      elif name.endswith(".setdefault"):
        k = str_arg(node)
        if k is not None:
          keys.add(k)
    elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
      targets = node.targets if isinstance(node, ast.Assign) else [node.target]
      for tgt in targets:
        if isinstance(tgt, ast.Subscript) and isinstance(tgt.slice, ast.Constant) \
            and isinstance(tgt.slice.value, str):
          keys.add(tgt.slice.value)
  return keys


class _Renderer:
  """URL-ish expression -> template string. Dynamic parts become `{x}`
  (or the placeholder's own name, so `/v1/kv/{key}` reads naturally)."""

  def __init__(self, env: Dict[str, ast.AST]):
    self.env = env

  def render(self, node: ast.AST, depth: int = 0) -> Optional[str]:
    if depth > 4:
      return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
      return node.value
    if isinstance(node, ast.JoinedStr):
      parts: List[str] = []
      for v in node.values:
        if isinstance(v, ast.Constant):
          parts.append(str(v.value))
        elif isinstance(v, ast.FormattedValue):
          name = dotted_name(v.value)
          parts.append("{" + (name.rsplit(".", 1)[-1] if name else "x") + "}")
      return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
      left = self.render(node.left, depth + 1) or "{x}"
      right = self.render(node.right, depth + 1) or "{x}"
      if left == "{x}" and right == "{x}":
        return None
      return left + right
    if isinstance(node, ast.IfExp):
      # A conditional query-string suffix (`f"?{...}" if query else ""`)
      # ends the path either way; any other conditional stays dynamic.
      branches = (self.render(node.body, depth + 1),
                  self.render(node.orelse, depth + 1))
      if all(b is not None and (b == "" or b.startswith("?")) for b in branches):
        return "?"
      return None
    if isinstance(node, ast.Name):
      bound = self.env.get(node.id)
      if bound is not None:
        return self.render(bound, depth + 1)
    return None


def _transport_of(call: ast.Call, env: Dict[str, ast.AST]) -> Optional[Tuple[str, Optional[str], Optional[str], bool]]:
  """Classify a Call as an HTTP transport: (kind, method, path, timeout).

  Session transports are `<...session>.get/post/delete/put(url, ...)` —
  the receiver's final name must contain "session" so `dict.get` never
  matches. Urllib transports are any `...urlopen(url_or_request, ...)`."""
  if not isinstance(call.func, ast.Attribute):
    return None
  rend = _Renderer(env)
  attr = call.func.attr
  recv = dotted_name(call.func.value)
  if attr in ("get", "post", "delete", "put") \
      and "session" in recv.rsplit(".", 1)[-1].lower():
    url = rend.render(call.args[0]) if call.args else None
    path = _path_of(url) if url else None
    timeout = any(kw.arg == "timeout" for kw in call.keywords)
    return ("session", attr.upper(), path, timeout)
  name = dotted_name(call.func)
  if name.endswith("urlopen"):
    method: Optional[str] = "GET"
    url_node: Optional[ast.AST] = call.args[0] if call.args else None
    if isinstance(url_node, ast.Name) and isinstance(env.get(url_node.id), ast.Call):
      url_node = env[url_node.id]
    if isinstance(url_node, ast.Call) and dotted_name(url_node.func).endswith("Request"):
      req = url_node
      method = None
      for kw in req.keywords:
        if kw.arg == "method" and isinstance(kw.value, ast.Constant):
          method = str(kw.value.value).upper()
        elif kw.arg == "data":
          method = method or "POST"
      method = method or "GET"
      url_node = req.args[0] if req.args else None
    url = rend.render(url_node) if url_node is not None else None
    path = _path_of(url) if url else None
    timeout = any(kw.arg == "timeout" for kw in call.keywords)
    return ("urllib", method, path, timeout)
  return None


class WireModel:
  """The full wire model; build once per repo via `wire_model(repo)`."""

  def __init__(self, repo: Repo):
    self.repo = repo
    self.prog: Program = program(repo)
    self.files: List[SourceFile] = self._scan_files()
    self.routes: List[Route] = []
    self.client_refs: List[ClientRef] = []
    self.transports: List[Transport] = []
    self.consumptions: List[Consumption] = []
    self.produced_global: Set[str] = set()
    self.bus_producers: List[BusSite] = []
    self.bus_arms: List[BusSite] = []
    # relpath -> True when every ClientSession(...) ctor in the module
    # carries timeout= (and at least one exists): per-call timeouts are
    # then redundant and not required.
    self.session_module_timeout: Dict[str, bool] = {}
    # Cross-file taint: attribute name -> route it was tainted from
    # (`rep.queue = q.get("admission")` makes every `.queue` read tainted).
    self.attr_taint: Dict[str, Optional[str]] = {}
    # Local fetch wrappers: bare name -> fixed route (or None when the
    # route varies per call and must render from the call's arguments).
    self.fetchers: Dict[str, Optional[str]] = {}
    self._closures: Dict[str, Set[str]] = {}
    self._method_index: Optional[Dict[str, List[str]]] = None
    self._build()

  # ------------------------------------------------------------------ scan

  def _scan_files(self) -> List[SourceFile]:
    files = [sf for sf in self.repo.files() if sf.tree is not None]
    in_pkg = {sf.relpath for sf in files}
    for root in TOOL_ROOTS:
      base = os.path.join(self.repo.root, root)
      if not os.path.isdir(base):
        continue
      for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
          if not name.endswith(".py"):
            continue
          rel = os.path.relpath(os.path.join(dirpath, name), self.repo.root)
          rel = rel.replace(os.sep, "/")
          if rel in in_pkg:
            continue
          sf = self.repo.file(rel)
          if sf is not None and sf.tree is not None:
            files.append(sf)
    return files

  def _build(self) -> None:
    dispatch_funcs: List[Tuple[SourceFile, str, Optional[str]]] = []
    for sf in self.files:
      self.produced_global |= _collect_keys(sf.tree)
      self._scan_static(sf, dispatch_funcs)
    route_paths = {r.path for r in self.routes}
    for sf in self.files:
      self._scan_loose(sf, route_paths)
    self._scan_taint()
    self._scan_bus_arms(dispatch_funcs)

  def _scan_static(self, sf: SourceFile,
                   dispatch_funcs: List[Tuple[SourceFile, str, Optional[str]]]) -> None:
    """Routes, ClientSession ctor policy, bus producers, dispatch handlers."""
    sessions: List[bool] = []
    for node in sf.nodes():
      if not isinstance(node, ast.Call):
        continue
      name = dotted_name(node.func)
      if isinstance(node.func, ast.Attribute) and node.func.attr in _ROUTE_REG \
          and node.args and len(node.args) >= 2:
        self._add_routes(sf, node)
      elif name.endswith("ClientSession"):
        sessions.append(any(kw.arg == "timeout" for kw in node.keywords))
      elif name.endswith("broadcast_opaque_status"):
        self._add_bus_producer(sf, node)
      elif isinstance(node.func, ast.Attribute) and node.func.attr == "on_next" \
          and isinstance(node.func.value, ast.Call) \
          and isinstance(node.func.value.func, ast.Attribute) \
          and node.func.value.func.attr == "register" and node.args:
        handler = dotted_name(node.args[0])
        if handler:
          dispatch_funcs.append(
            (sf, handler.rsplit(".", 1)[-1], sf.class_scope(node)))
    self.session_module_timeout[sf.relpath] = bool(sessions) and all(sessions)

  def _add_routes(self, sf: SourceFile, call: ast.Call) -> None:
    method = _ROUTE_REG[call.func.attr]
    paths: List[str] = []
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
      paths = [arg.value]
    elif isinstance(arg, ast.Name):
      # `for path in ("/v1/models", ...): r.add_get(path, handler)`
      anc = sf.parent(call)
      while anc is not None:
        if isinstance(anc, ast.For) and isinstance(anc.target, ast.Name) \
            and anc.target.id == arg.id \
            and isinstance(anc.iter, (ast.Tuple, ast.List)):
          paths = [e.value for e in anc.iter.elts
                   if isinstance(e, ast.Constant) and isinstance(e.value, str)]
          break
        anc = sf.parent(anc)
    handler = dotted_name(call.args[1])
    qual = None
    encl = sf.enclosing_func(call)
    if handler and encl is not None:
      info = self.prog.funcs.get(f"{sf.relpath}::{sf.qual(encl)}")
      if info is not None:
        qual = self.prog._resolve_name(info, handler)
    for path in paths:
      if _PATH_RE.match(path):
        self.routes.append(Route(method=method, path=path, handler=handler,
                                 handler_qual=qual, sf=sf, line=call.lineno))

  def _add_bus_producer(self, sf: SourceFile, call: ast.Call) -> None:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
      if isinstance(arg, ast.Call) and dotted_name(arg.func).endswith("dumps") \
          and arg.args and isinstance(arg.args[0], ast.Dict):
        d = arg.args[0]
        for k, v in zip(d.keys, d.values):
          if isinstance(k, ast.Constant) and k.value == "type" \
              and isinstance(v, ast.Constant) and isinstance(v.value, str):
            self.bus_producers.append(BusSite(v.value, sf, call.lineno))

  def _scan_bus_arms(self, dispatch_funcs: List[Tuple[SourceFile, str, Optional[str]]]) -> None:
    for sf, fname, cls in dispatch_funcs:
      fn = None
      for node in sf.nodes():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
            and node.name == fname and sf.class_scope(node) == cls:
          fn = node
          break
      if fn is None:
        continue
      # Names bound from `<x>.get("type", ...)` / `<x>["type"]`.
      type_names: Set[str] = set()
      for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
            and isinstance(node.targets[0], ast.Name):
          v = node.value
          if (isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)
              and v.func.attr == "get" and str_arg(v) == "type") or \
             (isinstance(v, ast.Subscript) and isinstance(v.slice, ast.Constant)
              and v.slice.value == "type"):
            type_names.add(node.targets[0].id)
      for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
          continue
        left = node.left
        is_type = (isinstance(left, ast.Name) and left.id in type_names) or \
                  (isinstance(left, ast.Call) and isinstance(left.func, ast.Attribute)
                   and left.func.attr == "get" and str_arg(left) == "type")
        if not is_type:
          continue
        for op, comp in zip(node.ops, node.comparators):
          if isinstance(op, (ast.Eq, ast.NotEq)) and isinstance(comp, ast.Constant) \
              and isinstance(comp.value, str):
            self.bus_arms.append(BusSite(comp.value, sf, node.lineno))
          elif isinstance(op, ast.In) and isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
            for e in comp.elts:
              if isinstance(e, ast.Constant) and isinstance(e.value, str):
                self.bus_arms.append(BusSite(e.value, sf, node.lineno))

  # ----------------------------------------------------------- client refs

  def _func_env(self, sf: SourceFile, fn: ast.AST) -> Dict[str, ast.AST]:
    """Single-assignment name bindings inside a function (URL rendering)."""
    env: Dict[str, ast.AST] = {}
    multi: Set[str] = set()
    for node in ast.walk(fn):
      if isinstance(node, ast.Assign):
        for tgt in node.targets:
          if isinstance(tgt, ast.Name):
            if tgt.id in env:
              multi.add(tgt.id)
            env[tgt.id] = node.value
    for name in multi:
      env.pop(name, None)
    return env

  def _scan_loose(self, sf: SourceFile, route_paths: Set[str]) -> None:
    """Transports + loose path references, per function/module scope."""
    envs: Dict[int, Dict[str, ast.AST]] = {}

    def env_for(node: ast.AST) -> Dict[str, ast.AST]:
      fn = sf.enclosing_func(node)
      key = id(fn)
      if key not in envs:
        envs[key] = self._func_env(sf, fn if fn is not None else sf.tree)
      return envs[key]

    def in_scope(path: str) -> bool:
      return path.startswith("/v1/") or path in route_paths

    url_args: Set[int] = set()
    for node in sf.nodes():
      if not isinstance(node, ast.Call):
        continue
      t = _transport_of(node, env_for(node))
      if t is None:
        continue
      kind, method, path, has_timeout = t
      scope = sf.func_scope(node)
      self.transports.append(Transport(
        kind=kind, method=method, path=path, sf=sf, call=node,
        line=node.lineno, scope=scope, has_timeout=has_timeout))
      if path is not None and in_scope(path):
        self.client_refs.append(ClientRef(
          path=path, method=method, sf=sf, line=node.lineno,
          scope=scope, kind=kind))
      for arg in ast.walk(node):
        url_args.add(id(arg))

    rend_cache: Dict[int, Optional[str]] = {}
    for node in sf.nodes():
      if id(node) in url_args:
        continue
      urlish: Optional[str] = None
      parent = sf.parent(node)
      if isinstance(node, ast.Constant) and isinstance(node.value, str):
        # Fragments of f-strings/concats render with their whole expression;
        # route REGISTRATIONS are servers, not clients.
        if isinstance(parent, (ast.JoinedStr, ast.BinOp)):
          continue
        if isinstance(parent, ast.Call) and isinstance(parent.func, ast.Attribute) \
            and parent.func.attr in _ROUTE_REG and parent.args \
            and parent.args[0] is node:
          continue
        urlish = node.value
      elif isinstance(node, ast.JoinedStr) and not isinstance(parent, ast.JoinedStr):
        urlish = _Renderer(env_for(node)).render(node)
      elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add) \
          and not isinstance(parent, (ast.BinOp, ast.JoinedStr)):
        urlish = _Renderer(env_for(node)).render(node)
      if not urlish:
        continue
      path = _path_of(urlish)
      # A bare "/" is string-manipulation vocabulary (`split("/")`,
      # `rstrip("/")`), never a root-route reference — transports only.
      if path is None or path == "/" or not in_scope(path):
        continue
      self.client_refs.append(ClientRef(
        path=path, method=None, sf=sf, line=node.lineno,
        scope=sf.func_scope(node), kind="loose"))

  # ----------------------------------------------------------------- taint

  def _scan_taint(self) -> None:
    """Fixpoint over fetch wrappers + tainted attributes, then one
    recording pass that emits consumptions."""
    fns: List[Tuple[SourceFile, ast.AST]] = []
    for sf in self.files:
      for node in sf.nodes():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
          fns.append((sf, node))
    for _ in range(4):
      before = (len(self.fetchers), len(self.attr_taint))
      for sf, fn in fns:
        _FnTaint(self, sf, fn).run(record=False)
      if (len(self.fetchers), len(self.attr_taint)) == before:
        break
    for sf, fn in fns:
      _FnTaint(self, sf, fn).run(record=True)

  # ------------------------------------------------------ produced closures

  def method_index(self) -> Dict[str, List[str]]:
    if self._method_index is None:
      idx: Dict[str, List[str]] = {}
      for qual in self.prog.funcs:
        name = qual.rsplit("::", 1)[1].rsplit(".", 1)[-1]
        idx.setdefault(name, []).append(qual)
      self._method_index = idx
    return self._method_index

  def produced_closure(self, handler_qual: str) -> Set[str]:
    """Every constant key a handler can put on the wire: BFS over resolved
    call/ref edges, widened by the bounded same-name fallback for calls
    resolution punts on (the `self.node.<subsystem>.<method>()` seam)."""
    memo = self._closures.get(handler_qual)
    if memo is not None:
      return memo
    keys: Set[str] = set()
    seen: Set[str] = set()
    frontier = [handler_qual]
    idx = self.method_index()
    while frontier:
      q = frontier.pop()
      if q in seen:
        continue
      seen.add(q)
      info = self.prog.funcs.get(q)
      if info is None:
        continue
      keys |= _collect_keys(info.node)
      nxt = list(info.edges)
      for unresolved in info.unresolved:
        name = unresolved.rsplit(".", 1)[-1]
        if name in _FALLBACK_STOP or name.startswith("__"):
          continue
        cands = idx.get(name, ())
        if 0 < len(cands) <= _FALLBACK_MAX_CANDIDATES:
          nxt.extend(cands)
      frontier.extend(n for n in nxt if n not in seen)
    self._closures[handler_qual] = keys
    return keys

  def routes_matching(self, path: str, method: Optional[str] = None) -> List[Route]:
    return [r for r in self.routes
            if path_match(path, r.path) and (method is None or r.method == method)]


class _FnTaint:
  """Per-function response-JSON taint: roots, propagation, consumption."""

  def __init__(self, wm: WireModel, sf: SourceFile, fn: ast.AST):
    self.wm = wm
    self.sf = sf
    self.fn = fn
    self.env = wm._func_env(sf, fn)
    self.rend = _Renderer(self.env)
    # with/async-with bindings: name -> (kind, route) for transport ctxs.
    self.resp: Dict[str, Tuple[str, Optional[str]]] = {}
    for node in ast.walk(fn):
      if isinstance(node, (ast.With, ast.AsyncWith)):
        for item in node.items:
          ctx = item.context_expr
          if isinstance(ctx, ast.Call) and isinstance(item.optional_vars, ast.Name):
            t = _transport_of(ctx, self.env)
            if t is not None:
              self.resp[item.optional_vars.id] = (t[0], t[2])
    self.tainted: Dict[str, Optional[str]] = {}

  def _route_of_call(self, call: ast.Call, fixed: Optional[str]) -> Optional[str]:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
      urlish = self.rend.render(arg)
      if urlish:
        path = _path_of(urlish)
        if path is not None:
          return path
    return fixed

  def taint(self, node: ast.AST, depth: int = 0):
    """None = untainted; else a 1-tuple (route,) so a None route still
    reads as a hit."""
    if depth > 8 or node is None:
      return None
    if isinstance(node, ast.Await):
      return self.taint(node.value, depth + 1)
    if isinstance(node, ast.NamedExpr):
      return self.taint(node.value, depth + 1)
    if isinstance(node, ast.Call):
      func = node.func
      name = dotted_name(func)
      if isinstance(func, ast.Attribute):
        if func.attr == "json" and isinstance(func.value, ast.Name):
          bound = self.resp.get(func.value.id)
          if bound is not None and bound[0] == "session":
            return (bound[1],)
        if func.attr == "get":
          base = self.taint(func.value, depth + 1)
          if base is not None:
            return base
      if name.endswith("loads"):
        for sub in ast.walk(node):
          if isinstance(sub, ast.Name):
            bound = self.resp.get(sub.id)
            if bound is not None and bound[0] == "urllib":
              return (bound[1],)
      short = name.rsplit(".", 1)[-1]
      if short in self.wm.fetchers:
        return (self._route_of_call(node, self.wm.fetchers[short]),)
      return None
    if isinstance(node, ast.Name):
      if node.id in self.tainted:
        return (self.tainted[node.id],)
      return None
    if isinstance(node, ast.Attribute):
      if node.attr in self.wm.attr_taint and not isinstance(node.ctx, ast.Store):
        return (self.wm.attr_taint[node.attr],)
      return None
    if isinstance(node, ast.Subscript):
      return self.taint(node.value, depth + 1)
    if isinstance(node, ast.BoolOp):
      for v in node.values:
        hit = self.taint(v, depth + 1)
        if hit is not None:
          return hit
      return None
    if isinstance(node, ast.IfExp):
      return self.taint(node.body, depth + 1) or self.taint(node.orelse, depth + 1)
    return None

  def run(self, record: bool) -> None:
    # Propagate through assignments; two passes cover use-before-bind
    # orderings inside loops.
    for _ in range(2):
      for node in ast.walk(self.fn):
        if not isinstance(node, ast.Assign):
          continue
        hit = self.taint(node.value)
        if hit is None:
          continue
        for tgt in node.targets:
          if isinstance(tgt, ast.Name):
            self.tainted[tgt.id] = hit[0]
          elif isinstance(tgt, ast.Attribute):
            self.wm.attr_taint.setdefault(tgt.attr, hit[0])
    # Fetch-wrapper detection: the function RETURNS tainted data.
    short = self.fn.name
    for node in ast.walk(self.fn):
      if isinstance(node, ast.Return) and node.value is not None:
        hit = self.taint(node.value)
        if hit is not None and short not in self.wm.fetchers:
          self.wm.fetchers[short] = hit[0]
    if not record:
      return
    for node in ast.walk(self.fn):
      key: Optional[str] = None
      base: Optional[ast.AST] = None
      if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
          and node.func.attr == "get":
        key = str_arg(node)
        base = node.func.value
      elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load) \
          and isinstance(node.slice, ast.Constant) and isinstance(node.slice.value, str):
        key = node.slice.value
        base = node.value
      if key is None or base is None:
        continue
      hit = self.taint(base)
      if hit is None:
        continue
      self.wm.consumptions.append(Consumption(
        key=key, route=hit[0], sf=self.sf, line=node.lineno,
        scope=self.sf.func_scope(node)))


def wire_model(repo: Repo) -> WireModel:
  """The memoized wire model (one build shared by the four checkers)."""
  wm = getattr(repo, "_xotlint_wire", None)
  if wm is None:
    wm = WireModel(repo)
    repo._xotlint_wire = wm
  return wm
