"""Whole-program callgraph / dataflow core for xotlint.

The per-function checkers (PR 5) stop at the def boundary, but the PR 6-9
hot-path invariants are properties of PATHS: "no host sync reachable from
the decode dispatch entry points", "no callback invoked while a lock
acquired three calls up is still held". This module builds the shared
module-level callgraph those checkers escalate onto:

- **module map**: every top-level `def`, every `class` with its methods
  (and nested defs), keyed `path::Class.method` / `path::func`;
- **import resolution** for absolute package imports, both
  `from pkg.mod import name [as alias]` and `import pkg.mod [as alias]`;
- **method resolution through `self`**: own methods first, then base
  classes resolvable through imports (cycle-safe);
- **attribute typing**: `self.attr = param` in `__init__` where the param
  is annotated with a resolvable class name (string annotations included)
  types later `self.attr.method()` calls — the `_DecodeBatcher.engine ->
  JAXShardInferenceEngine` seam that makes the drain loop analyzable;
- **reference edges**: a known function passed as a Call ARGUMENT is an
  edge (`self._run(self._decode_batch_sync, ...)` — executor indirection
  is how the engine dispatches everything);
- **reachability**: cycle-tolerant BFS. Unresolved callees (stdlib, jax,
  dynamic attributes, parameters called as functions) are recorded on the
  FuncInfo but never expand the frontier — conservative for a lint whose
  baseline policy is "empty": a silent miss is caught by the dynamic
  monkeypatch tests, a false positive would train people to suppress.

Also home to the **jit-site table** (`jit_sites`): every `jax.jit` call or
`@partial(jax.jit, ...)` decoration with its wrapped def, static names and
donate positions — shared by retrace-hazard and donation-safety.

Everything is memoized on the Repo (`program(repo)` / `jit_sites(repo)`),
so the four whole-program checkers pay for one build.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.xotlint.core import Repo, SourceFile, dotted_name

_PKG = "xotorch_tpu"


@dataclass
class FuncInfo:
  """One function/method in the program, with its resolved out-edges."""
  qual: str                      # "relpath::Class.method" / "relpath::func"
  node: ast.AST                  # the FunctionDef / AsyncFunctionDef
  sf: SourceFile
  cls: Optional[str]             # innermost enclosing class name, if a method
  calls: List[str] = field(default_factory=list)       # resolved callee quals
  refs: List[str] = field(default_factory=list)        # taken-as-value quals
  unresolved: List[str] = field(default_factory=list)  # dotted names we punted on

  @property
  def edges(self) -> List[str]:
    return self.calls + self.refs


class _Module:
  """Per-file symbol tables feeding resolution."""

  def __init__(self, sf: SourceFile):
    self.sf = sf
    self.funcs: Dict[str, str] = {}          # top-level def name -> qual
    self.classes: Dict[str, "_Class"] = {}
    # import alias -> ("mod", relpath) | ("sym", relpath, name)
    self.imports: Dict[str, tuple] = {}


class _Class:
  def __init__(self, name: str, relpath: str):
    self.name = name
    self.relpath = relpath
    self.methods: Dict[str, str] = {}        # method name -> qual
    self.bases: List[str] = []               # base names as written
    self.attr_types: Dict[str, str] = {}     # self.attr -> class dotted name


def _mod_relpath(dotted: str) -> Optional[str]:
  """`xotorch_tpu.models.generate` -> `xotorch_tpu/models/generate.py`."""
  if dotted != _PKG and not dotted.startswith(_PKG + "."):
    return None
  return dotted.replace(".", "/") + ".py"


class Program:
  """The whole-program view: symbol tables + resolved call/ref edges."""

  def __init__(self, repo: Repo):
    self.repo = repo
    self.modules: Dict[str, _Module] = {}
    self.funcs: Dict[str, FuncInfo] = {}
    self._build()

  # ------------------------------------------------------------------ build

  def _build(self) -> None:
    for sf in self.repo.files():
      if sf.tree is not None:
        self._collect_module(sf)
    for sf in self.repo.files():
      if sf.tree is not None:
        self._collect_attr_types(sf)
    for info in list(self.funcs.values()):
      self._resolve_edges(info)

  def _collect_module(self, sf: SourceFile) -> None:
    mod = self.modules[sf.relpath] = _Module(sf)
    for node in sf.nodes():
      if isinstance(node, (ast.Import, ast.ImportFrom)):
        self._collect_import(mod, node)
      elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        qual = f"{sf.relpath}::{sf.qual(node)}"
        cls = sf.class_scope(node)
        self.funcs[qual] = FuncInfo(qual=qual, node=node, sf=sf, cls=cls)
        if sf.enclosing_func(node) is None:
          if cls is None:
            mod.funcs[node.name] = qual
          else:
            c = mod.classes.get(cls)
            if c is not None:
              c.methods[node.name] = qual
      elif isinstance(node, ast.ClassDef) and sf.enclosing_func(node) is None \
          and sf.class_scope(node) is None:
        c = mod.classes[node.name] = _Class(node.name, sf.relpath)
        c.bases = [dotted_name(b) for b in node.bases if dotted_name(b)]

  def _collect_import(self, mod: _Module, node: ast.AST) -> None:
    if isinstance(node, ast.Import):
      for alias in node.names:
        rel = _mod_relpath(alias.name)
        if rel is not None:
          # `import xotorch_tpu.models.generate as g` binds g to the module;
          # un-aliased imports bind the package root name (attribute chains
          # resolve through the full dotted call name instead).
          mod.imports[alias.asname or alias.name] = ("mod", rel)
    elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
      rel = _mod_relpath(node.module)
      if rel is None:
        return
      for alias in node.names:
        # `from pkg.a import b` where pkg/a/b.py exists imports the MODULE.
        sub = _mod_relpath(f"{node.module}.{alias.name}")
        local = alias.asname or alias.name
        if sub is not None and self._exists(sub):
          mod.imports[local] = ("mod", sub)
        else:
          mod.imports[local] = ("sym", rel, alias.name)

  def _exists(self, relpath: str) -> bool:
    return any(sf.relpath == relpath for sf in self.repo.files())

  def _collect_attr_types(self, sf: SourceFile) -> None:
    """`self.attr = param` in __init__ with an annotated param whose type
    resolves to a known class -> attr_types entry for method resolution
    through `self.attr.method()`."""
    mod = self.modules[sf.relpath]
    for cls in mod.classes.values():
      init_qual = cls.methods.get("__init__")
      if init_qual is None:
        continue
      init = self.funcs[init_qual].node
      ann: Dict[str, str] = {}
      for a in init.args.args + init.args.kwonlyargs:
        t = a.annotation
        if isinstance(t, ast.Constant) and isinstance(t.value, str):
          ann[a.arg] = t.value.strip("'\" ")
        elif t is not None and dotted_name(t):
          ann[a.arg] = dotted_name(t)
      for stmt in ast.walk(init):
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1):
          continue
        tgt = stmt.targets[0]
        if (isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self" and isinstance(stmt.value, ast.Name)):
          ty = ann.get(stmt.value.id)
          if ty and self._resolve_class(mod, ty.split("[")[0]) is not None:
            cls.attr_types[tgt.attr] = ty.split("[")[0]

  # -------------------------------------------------------------- resolution

  def _resolve_class(self, mod: _Module, name: str) -> Optional[_Class]:
    """A class name as written in `mod` (local or imported symbol)."""
    head, _, rest = name.partition(".")
    if rest:
      imp = mod.imports.get(head)
      if imp is not None and imp[0] == "mod":
        target = self.modules.get(imp[1])
        return target.classes.get(rest) if target and "." not in rest else None
      return None
    c = mod.classes.get(name)
    if c is not None:
      return c
    imp = mod.imports.get(name)
    if imp is not None and imp[0] == "sym":
      target = self.modules.get(imp[1])
      if target is not None:
        return target.classes.get(imp[2])
    return None

  def _method_on(self, mod: _Module, cls: _Class, method: str,
                 _seen: Optional[Set[str]] = None) -> Optional[str]:
    """Method lookup on a class, walking resolvable bases (cycle-safe)."""
    seen = _seen or set()
    key = f"{cls.relpath}::{cls.name}"
    if key in seen:
      return None
    seen.add(key)
    q = cls.methods.get(method)
    if q is not None:
      return q
    base_mod = self.modules.get(cls.relpath)
    for base in cls.bases:
      bc = self._resolve_class(base_mod or mod, base)
      if bc is not None:
        q = self._method_on(self.modules.get(bc.relpath, mod), bc, method, seen)
        if q is not None:
          return q
    return None

  def _resolve_name(self, info: FuncInfo, name: str) -> Optional[str]:
    """A dotted name in `info`'s body -> callee qual, or None (unresolved).

    Classes resolve to their __init__ (instantiation executes it)."""
    if not name:
      return None
    mod = self.modules[info.sf.relpath]
    parts = name.split(".")

    if parts[0] == "self" and info.cls is not None:
      cls = mod.classes.get(info.cls)
      if cls is None:
        return None
      if len(parts) == 2:
        return self._method_on(mod, cls, parts[1])
      if len(parts) == 3:
        ty = cls.attr_types.get(parts[1])
        if ty is not None:
          tc = self._resolve_class(mod, ty)
          if tc is not None:
            return self._method_on(self.modules.get(tc.relpath, mod), tc, parts[2])
      return None

    # Nested defs visible from the enclosing function scope chain.
    if len(parts) == 1:
      scope = info.qual.split("::", 1)[1]
      chain = scope.split(".")
      for i in range(len(chain), 0, -1):
        q = f"{info.sf.relpath}::{'.'.join(chain[:i])}.{name}"
        if q in self.funcs:
          return q

    head_imp = mod.imports.get(parts[0])
    if head_imp is not None:
      if head_imp[0] == "sym":
        target = self.modules.get(head_imp[1])
        if target is None:
          return None
        if len(parts) == 1:
          q = target.funcs.get(head_imp[2])
          if q is not None:
            return q
          c = target.classes.get(head_imp[2])
          return c.methods.get("__init__") if c is not None else None
        c = target.classes.get(head_imp[2])
        if c is not None and len(parts) == 2:
          return self._method_on(target, c, parts[1])
        return None
      # module alias
      target = self.modules.get(head_imp[1])
      if target is None or len(parts) == 1:
        return None
      if len(parts) == 2:
        q = target.funcs.get(parts[1])
        if q is not None:
          return q
        c = target.classes.get(parts[1])
        return c.methods.get("__init__") if c is not None else None
      if len(parts) == 3:
        c = target.classes.get(parts[1])
        if c is not None:
          return self._method_on(target, c, parts[2])
      return None

    if len(parts) == 1:
      q = mod.funcs.get(name)
      if q is not None:
        return q
      c = mod.classes.get(name)
      if c is not None:
        return c.methods.get("__init__")
      return None
    if len(parts) == 2:
      c = mod.classes.get(parts[0])
      if c is not None:
        return self._method_on(mod, c, parts[1])
    # Fully-dotted absolute call (import xotorch_tpu; xotorch_tpu.x.f()).
    rel = _mod_relpath(".".join(parts[:-1]))
    if rel is not None and rel in self.modules:
      return self.modules[rel].funcs.get(parts[-1])
    return None

  def _resolve_edges(self, info: FuncInfo) -> None:
    sf = info.sf
    for node in ast.walk(info.node):
      if node is not info.node and sf.enclosing_func(node) is None:
        continue  # defensive; walk stays inside the def
      if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        q = self._resolve_name(info, name)
        if q is not None:
          info.calls.append(q)
        elif name:
          info.unresolved.append(name)
        # Function references in argument position: executor indirection
        # (`self._run(self._decode_batch_sync, ...)`), thunk registration.
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
          rname = dotted_name(arg)
          if rname:
            rq = self._resolve_name(info, rname)
            if rq is not None and rq != q:
              info.refs.append(rq)

  # ------------------------------------------------------------ reachability

  def find(self, suffix: str) -> List[str]:
    """Quals whose `path::qual` ends with `suffix` (declaration ergonomics:
    entry points name `engine.py::Class.method` without the full path)."""
    return [q for q in self.funcs if q == suffix or q.endswith(suffix)]

  def reachable(self, entry_suffixes: Sequence[str]) -> Dict[str, List[str]]:
    """BFS closure over call+ref edges from the entry points. Returns
    {qual: path-of-quals from its entry} — the witness chain findings
    print. Cycle-tolerant: first visit wins."""
    chains: Dict[str, List[str]] = {}
    frontier: List[str] = []
    for s in entry_suffixes:
      for q in self.find(s):
        if q not in chains:
          chains[q] = [q]
          frontier.append(q)
    while frontier:
      q = frontier.pop()
      info = self.funcs.get(q)
      if info is None:
        continue
      for callee in info.edges:
        if callee not in chains:
          chains[callee] = chains[q] + [callee]
          frontier.append(callee)
    return chains


def program(repo: Repo) -> Program:
  """The memoized whole-program view (one build shared by all checkers)."""
  prog = getattr(repo, "_xotlint_program", None)
  if prog is None:
    prog = Program(repo)
    repo._xotlint_program = prog
  return prog


# ------------------------------------------------------------------ jit sites

@dataclass
class JitSite:
  """One `jax.jit` application: decorator or call."""
  sf: SourceFile
  line: int
  name: str                      # wrapped func name, assignment target, or key
  func_node: Optional[ast.AST]   # the wrapped def, when visible in-file
  static_names: Tuple[str, ...] = ()
  donate_names: Tuple[str, ...] = ()
  params: Tuple[str, ...] = ()   # wrapped def's positional params, if known
  donate_positions: Tuple[int, ...] = ()
  factory: Optional[str] = None  # enclosing function qual that RETURNS this jit


def _const_tuple(node: ast.AST) -> Tuple:
  if isinstance(node, ast.Constant):
    return (node.value,)
  if isinstance(node, (ast.Tuple, ast.List)):
    return tuple(e.value for e in node.elts if isinstance(e, ast.Constant))
  return ()


def _is_jit_call(node: ast.Call) -> bool:
  return dotted_name(node.func) in ("jax.jit", "jit")


def _partial_of_jit(node: ast.Call) -> Optional[ast.Call]:
  """`partial(jax.jit, ...)` / `functools.partial(jax.jit, ...)` -> node."""
  if dotted_name(node.func) in ("partial", "functools.partial") and node.args:
    head = node.args[0]
    if isinstance(head, ast.Attribute) or isinstance(head, ast.Name):
      if dotted_name(head) in ("jax.jit", "jit"):
        return node
  return None


def _unwrap_partial(node: ast.AST) -> Tuple[Optional[str], Dict[str, ast.AST]]:
  """`partial(fwd, use_flash=True)` -> ("fwd", {use_flash: ...});
  a bare Name -> (name, {}). Anything else -> (None, {})."""
  if isinstance(node, ast.Name):
    return node.id, {}
  if isinstance(node, ast.Call) and dotted_name(node.func) in ("partial", "functools.partial"):
    if node.args and isinstance(node.args[0], (ast.Name, ast.Attribute)):
      return dotted_name(node.args[0]) or None, {kw.arg: kw.value for kw in node.keywords if kw.arg}
  return None, {}


def _def_params(fn: ast.AST) -> Tuple[str, ...]:
  a = fn.args
  return tuple(p.arg for p in a.posonlyargs + a.args)


def _site_from_kw(sf: SourceFile, line: int, name: str, func_node, keywords,
                  factory=None) -> JitSite:
  static: Tuple[str, ...] = ()
  donate_names: Tuple[str, ...] = ()
  donate_pos: Tuple[int, ...] = ()
  params = _def_params(func_node) if func_node is not None else ()
  for kw in keywords:
    if kw.arg == "static_argnames":
      static = tuple(str(v) for v in _const_tuple(kw.value))
    elif kw.arg == "static_argnums":
      nums = tuple(int(v) for v in _const_tuple(kw.value) if isinstance(v, int))
      static = static + tuple(params[i] for i in nums if i < len(params))
    elif kw.arg == "donate_argnames":
      donate_names = tuple(str(v) for v in _const_tuple(kw.value))
    elif kw.arg == "donate_argnums":
      donate_pos = tuple(int(v) for v in _const_tuple(kw.value) if isinstance(v, int))
  if donate_names and params:
    donate_pos = donate_pos + tuple(params.index(n) for n in donate_names if n in params)
  return JitSite(sf=sf, line=line, name=name, func_node=func_node,
                 static_names=static, donate_names=donate_names,
                 params=params, donate_positions=donate_pos, factory=factory)


def jit_sites(repo: Repo) -> List[JitSite]:
  """Every jax.jit application in the tree (memoized on the repo)."""
  sites = getattr(repo, "_xotlint_jit_sites", None)
  if sites is not None:
    return sites
  sites = []
  for sf in repo.files():
    if sf.tree is None:
      continue
    local_defs: Dict[str, ast.AST] = {}
    for node in sf.nodes():
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        local_defs.setdefault(node.name, node)
    for node in sf.nodes():
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for dec in node.decorator_list:
          if isinstance(dec, ast.Call) and (_partial_of_jit(dec) or _is_jit_call(dec)):
            sites.append(_site_from_kw(sf, node.lineno, node.name, node, dec.keywords))
          elif dotted_name(dec) in ("jax.jit", "jit"):
            sites.append(JitSite(sf=sf, line=node.lineno, name=node.name,
                                 func_node=node, params=_def_params(node)))
      elif isinstance(node, ast.Call) and _is_jit_call(node) and node.args:
        base_name, _bound = _unwrap_partial(node.args[0])
        func_node = local_defs.get(base_name) if base_name else None
        # Site name: the assignment target when there is one (that is the
        # callable's name at call sites), else the wrapped function's name.
        name = base_name or "<dynamic>"
        stmt = node
        while stmt is not None and not isinstance(stmt, ast.stmt):
          stmt = sf.parent(stmt)
        if isinstance(stmt, ast.Assign) and stmt.targets:
          tgt = stmt.targets[0]
          tn = dotted_name(tgt)
          if tn:
            name = tn.rsplit(".", 1)[-1]
          elif isinstance(tgt, ast.Subscript) and isinstance(tgt.slice, ast.Constant):
            name = str(tgt.slice.value)
        factory = None
        fn = sf.enclosing_func(node)
        if fn is not None:
          # A factory returns the jitted callable (the lazy-jit idiom:
          # `_commit_jit()(args...)`): the jit call's value flows to a
          # `return` of the function, directly or through one local name.
          names = {name}
          if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
              if isinstance(t, ast.Name):
                names.add(t.id)
          for n2 in ast.walk(fn):
            if isinstance(n2, ast.Return) and n2.value is not None:
              rv = n2.value
              if rv is node or (isinstance(rv, ast.Name) and rv.id in names):
                factory = f"{sf.relpath}::{sf.qual(fn)}"
        sites.append(_site_from_kw(sf, node.lineno, name, func_node,
                                   node.keywords, factory=factory))
  repo._xotlint_jit_sites = sites
  return sites
