"""metrics-consistency checker: what's incremented is what's exported.

The exported surface has three layers, all parsed statically:

- `NodeMetrics` registry metrics: `self.<attr> = Counter|Gauge|Histogram(
  "xot_...", ...)` in orchestration/metrics.py — yields attr -> (name, type);
- exposition-appended process counters in metrics.py (`("bump_key",
  "xot_..._total", help)` tuples over `faults.COUNTERS`);
- engine counters/gauges the API appends in chatgpt_api.py
  (`("_attr", "xot_...", help)` tuples in handle_get_metrics), typed by
  the `# TYPE ... counter|gauge` f-string inside the same loop.

Checks:

- `unknown-metric-attr`: `.inc()/.observe()/.set()` on `metrics.<attr>`
  where NodeMetrics defines no such attr — the increment raises (or worse,
  targets a metric that exists nowhere) at runtime;
- `counter-name-convention`: a counter not ending `_total`, or a
  gauge/histogram ending `_total`;
- `unexported-counter`: a `faults.bump("key")` whose `xot_<key>_total`
  line no NodeMetrics.exposition appends;
- `dead-exported-counter`: an engine counter attr the API exports but no
  engine code ever increments (`self.<attr> += ...`);
- `unknown-flight-event` / `dead-flight-event`: every
  `<recorder>.record("<subsystem>.<event>", ...)` literal must be declared
  in orchestration/flight.py's `EVENTS` tuple (a typo'd string raises at
  runtime — fail it in CI instead), and every declared event must be
  recorded somewhere (a dead name means the instrumentation it documents
  was removed or never landed);
- `dead-exported-gauge`: an API exposition row keyed on a STATS-DICT key
  (pool occupancy, host tier, perf-attribution gauges — rows whose first
  element is not an engine `_attr`) must resolve to a key some engine-side
  code actually produces (a dict-literal key or `d["key"] = ...` store) —
  otherwise the exported series silently KeyErrors or reads a value that
  exists nowhere;
- `unknown-alert-metric`: every `AlertRule(...)` metric reference in
  orchestration/alerts.py must resolve against the statically extracted
  surface — `family="x"` to an exported histogram `xot_x`, `bad=`/`total=`
  to an exported counter `xot_x_total`. A typo'd reference evaluates to
  "no data" forever: the rule silently never fires, which is the worst
  possible failure mode for an alert.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.xotlint.core import Finding, Repo, dotted_name, str_arg

CHECKER = "metrics-consistency"

_METRIC_NAME_RE = re.compile(r"^xot_[a-z0-9_]+$")
_CTORS = {"Counter": "counter", "Gauge": "gauge", "Histogram": "histogram"}
# Flight events are `<subsystem>.<event>` — distinctive enough that any
# `.record("a.b", ...)` call is treated as a flight-recorder site
# regardless of how the receiver is spelled (self.flight.record, a local
# alias, a peer handle's attached recorder).
_FLIGHT_EVENT_RE = re.compile(r"^[a-z0-9_]+\.[a-z0-9_]+$")


def _inner_ctor(node: ast.AST) -> Optional[Tuple[str, str]]:
  """(metric_name, metric_type) from a `Counter("name", ...)...` chain."""
  for call in ast.walk(node):
    if isinstance(call, ast.Call):
      fn = dotted_name(call.func).rsplit(".", 1)[-1]
      if fn in _CTORS:
        name = str_arg(call)
        if name is not None:
          return name, _CTORS[fn]
  return None


def registry_metrics(repo: Repo) -> Dict[str, Tuple[str, str]]:
  """attr -> (metric_name, metric_type) from NodeMetrics.__init__.

  Two assignment shapes are resolved: the direct chain
  `self.x = Histogram("xot_...", ...).labels(...)`, and the shared-parent
  shape for labeled families — `h = Histogram("xot_...", ["node_id",
  "lane"], ...)` followed by `self.a = h.labels(lane="decode")` — where
  several attrs expose one metric name under different label values."""
  sf = repo.file(repo.metrics_path)
  out: Dict[str, Tuple[str, str]] = {}
  if sf is None or sf.tree is None:
    return out
  var_ctors: Dict[str, Tuple[str, str]] = {}
  for node in sf.nodes():
    if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
      continue
    target = node.targets[0]
    ctor = _inner_ctor(node.value)
    if isinstance(target, ast.Name) and ctor is not None:
      var_ctors[target.id] = ctor
    elif isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name) \
        and target.value.id == "self":
      if ctor is not None:
        out[target.attr] = ctor
      else:
        # `self.attr = <var>.labels(...)`: resolve through the local ctor.
        for name in ast.walk(node.value):
          if isinstance(name, ast.Name) and name.id in var_ctors:
            out[target.attr] = var_ctors[name.id]
            break
  return out


def _tuple_table(sf) -> List[Tuple[ast.For, List[Tuple[str, str, int]]]]:
  """For-loops iterating literal ((key, "xot_name", help), ...) tables:
  [(loop, [(key, metric_name, line), ...]), ...]."""
  out = []
  for node in sf.nodes():
    if not isinstance(node, ast.For):
      continue
    rows: List[Tuple[str, str, int]] = []
    for tup in ast.walk(node.iter):
      if isinstance(tup, ast.Tuple) and len(tup.elts) >= 2:
        first, second = tup.elts[0], tup.elts[1]
        if isinstance(first, ast.Constant) and isinstance(first.value, str) \
            and isinstance(second, ast.Constant) and isinstance(second.value, str) \
            and _METRIC_NAME_RE.match(second.value):
          rows.append((first.value, second.value, tup.lineno))
    if rows:
      out.append((node, rows))
  return out


def _loop_metric_type(loop: ast.For) -> Optional[str]:
  """counter/gauge from the `# TYPE {name} counter` f-string in the body.
  F-strings split their literal text across Constant pieces, so join each
  JoinedStr before matching."""
  texts = []
  for node in ast.walk(loop):
    if isinstance(node, ast.JoinedStr):
      texts.append("".join(
        v.value for v in node.values
        if isinstance(v, ast.Constant) and isinstance(v.value, str)))
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
      texts.append(node.value)
  for text in texts:
    if "TYPE" in text and " counter" in text:
      return "counter"
    if "TYPE" in text and " gauge" in text:
      return "gauge"
  return None


def exported_metrics(repo: Repo) -> Dict[str, str]:
  """metric_name -> type across the whole exported surface."""
  exported: Dict[str, str] = {}
  for attr, (name, mtype) in registry_metrics(repo).items():
    exported[name] = mtype
  for path in (repo.metrics_path, repo.api_metrics_path):
    sf = repo.file(path)
    if sf is None or sf.tree is None:
      continue
    for loop, rows in _tuple_table(sf):
      mtype = _loop_metric_type(loop) or "counter"
      for _, name, _ in rows:
        exported[name] = mtype
  return exported


def flight_events(repo: Repo) -> Dict[str, int]:
  """name -> declaration line for the `EVENTS` literal tuple in flight.py
  (empty when the tree has no flight module — fixture repos)."""
  sf = repo.file(repo.flight_path)
  out: Dict[str, int] = {}
  if sf is None or sf.tree is None:
    return out
  for node in sf.nodes():
    if isinstance(node, ast.Assign) and len(node.targets) == 1 \
        and isinstance(node.targets[0], ast.Name) and node.targets[0].id == "EVENTS":
      for elt in ast.walk(node.value):
        if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
          out[elt.value] = elt.lineno
  return out


def _flight_record_sites(repo: Repo) -> List[Tuple[str, str, int]]:
  """(event, path, line) for every `<recorder>.record("<a>.<b>", ...)`."""
  sites = []
  for sf in repo.files():
    if sf.tree is None:
      continue
    for node in sf.nodes():
      if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
          and node.func.attr == "record":
        event = str_arg(node)
        if event is not None and _FLIGHT_EVENT_RE.match(event):
          sites.append((event, sf.relpath, node.lineno))
  return sites


def alert_rule_refs(repo: Repo) -> List[Tuple[str, str, int]]:
  """(kwarg, referenced-name, line) for every string `family=`/`bad=`/
  `total=` keyword of an `AlertRule(...)` call in the alerts module."""
  sf = repo.file(repo.alerts_path)
  rows: List[Tuple[str, str, int]] = []
  if sf is None or sf.tree is None:
    return rows
  for node in sf.nodes():
    if isinstance(node, ast.Call) \
        and dotted_name(node.func).rsplit(".", 1)[-1] == "AlertRule":
      for kw in node.keywords:
        if kw.arg in ("family", "bad", "total") and isinstance(kw.value, ast.Constant) \
            and isinstance(kw.value.value, str) and kw.value.value:
          rows.append((kw.arg, kw.value.value, node.lineno))
  return rows


def _bump_sites(repo: Repo) -> List[Tuple[str, str, int]]:
  """(key, path, line) for every faults.bump("key") call."""
  sites = []
  for sf in repo.files():
    if sf.tree is None:
      continue
    for node in sf.nodes():
      if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn == "bump" or fn.endswith(".bump"):
          key = str_arg(node)
          if key is not None:
            sites.append((key, sf.relpath, node.lineno))
  return sites


def _metrics_attr_calls(repo: Repo) -> List[Tuple[str, str, str, int]]:
  """(attr, method, path, line) for `<x>.metrics.<attr>.inc/observe/set(...)`."""
  calls = []
  for sf in repo.files():
    if sf.tree is None:
      continue
    for node in sf.nodes():
      if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
          and node.func.attr in ("inc", "observe", "set", "dec"):
        chain = dotted_name(node.func)
        parts = chain.split(".")
        if len(parts) >= 3 and parts[-3] == "metrics":
          calls.append((parts[-2], node.func.attr, sf.relpath, node.lineno))
  return calls


def _produced_dict_keys(repo: Repo) -> Set[str]:
  """String keys any code in the tree produces into a dict: literal
  `{"key": ...}` entries and `d["key"] = ...` subscript stores. The
  resolution set for exposition rows that read engine stats dicts
  (page_pool_stats / host_kv_stats / perf_stats)."""
  keys: Set[str] = set()
  for sf in repo.files():
    if sf.tree is None:
      continue
    for node in sf.nodes():
      if isinstance(node, ast.Dict):
        for k in node.keys:
          if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
      elif isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for target in targets:
          if isinstance(target, ast.Subscript) \
              and isinstance(target.slice, ast.Constant) \
              and isinstance(target.slice.value, str):
            keys.add(target.slice.value)
  return keys


def _engine_aug_attrs(repo: Repo) -> Set[str]:
  """self.<attr> names actually INCREMENTED anywhere in the tree: `+=`, or
  an assignment whose RHS reads the same attr (`x.a = x.a + n`). A plain
  initialization (`self._oom_count = 0`) is not an increment — counting it
  would let a counter whose only remaining reference is its __init__ zero
  keep passing the dead-exported-counter check forever."""
  attrs: Set[str] = set()
  for sf in repo.files():
    if sf.tree is None:
      continue
    for node in sf.nodes():
      if isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Attribute):
        attrs.add(node.target.attr)
      elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
          and isinstance(node.targets[0], ast.Attribute):
        attr = node.targets[0].attr
        if any(isinstance(n, ast.Attribute) and n.attr == attr
               for n in ast.walk(node.value)):
          attrs.add(attr)
  return attrs


def check(repo: Repo) -> List[Finding]:
  findings: List[Finding] = []
  reg = registry_metrics(repo)
  exported = exported_metrics(repo)

  # Name conventions across the whole exported surface.
  metrics_sf = repo.file(repo.metrics_path)
  for name, mtype in sorted(exported.items()):
    is_counter_name = name.endswith("_total")
    if mtype == "counter" and not is_counter_name:
      findings.append(Finding(
        CHECKER, "counter-name-convention", repo.metrics_path, 1, key=name,
        message=f"counter `{name}` must end in `_total` (prometheus counter convention)",
      ))
    elif mtype in ("gauge", "histogram") and is_counter_name:
      findings.append(Finding(
        CHECKER, "counter-name-convention", repo.metrics_path, 1, key=name,
        message=f"{mtype} `{name}` must not end in `_total` — that suffix promises a counter",
      ))

  # Every metrics.<attr> touch resolves to a NodeMetrics attribute.
  # Throughout: suppressed() is consulted only once a violation is
  # ESTABLISHED — its hit-recording side effect feeds the stale-suppression
  # audit, so querying it for clean lines would mark dead comments as earned.
  for attr, method, path, line in _metrics_attr_calls(repo):
    if attr not in reg:
      sf = repo.file(path)
      if sf is not None and sf.suppressed(line, CHECKER):
        continue
      findings.append(Finding(
        CHECKER, "unknown-metric-attr", path, line, key=f"{attr}.{method}",
        message=f"`metrics.{attr}.{method}()` but NodeMetrics defines no `{attr}` "
                "— this raises AttributeError on the serving path",
      ))

  # Every bump("key") is exported as xot_<key>_total by the exposition.
  exposition_names = set(exported)
  for key, path, line in _bump_sites(repo):
    want = f"xot_{key}_total"
    if want not in exposition_names:
      sf = repo.file(path)
      if sf is not None and sf.suppressed(line, CHECKER):
        continue
      findings.append(Finding(
        CHECKER, "unexported-counter", path, line, key=key,
        message=f"`bump(\"{key}\")` increments a process counter but "
                f"NodeMetrics.exposition never appends `{want}` — the count is invisible",
      ))

  # Flight events: every record-site literal is declared in EVENTS, and
  # every declared event is recorded somewhere in the tree.
  declared = flight_events(repo)
  if declared:
    recorded: Set[str] = set()
    for event, path, line in _flight_record_sites(repo):
      recorded.add(event)
      if event not in declared:
        sf = repo.file(path)
        if sf is not None and sf.suppressed(line, CHECKER):
          continue
        findings.append(Finding(
          CHECKER, "unknown-flight-event", path, line, key=event,
          message=f"`.record(\"{event}\")` but orchestration/flight.py EVENTS does "
                  "not declare it — this raises ValueError on the serving path",
        ))
    for event, line in sorted(declared.items()):
      if event not in recorded:
        findings.append(Finding(
          CHECKER, "dead-flight-event", repo.flight_path, line, key=event,
          message=f"flight event `{event}` is declared but nothing records it — "
                  "remove it or restore the instrumentation",
        ))

  # Alert-rule metric references resolve against the extracted surface:
  # a latency rule's family must be an exported histogram, an error rule's
  # bad/total counters must export as xot_<name>_total.
  alerts_sf = repo.file(repo.alerts_path)
  for kwarg, ref, line in alert_rule_refs(repo):
    if kwarg == "family":
      want, want_type = f"xot_{ref}", "histogram"
    else:
      want, want_type = f"xot_{ref}_total", "counter"
    if exported.get(want) != want_type:
      if alerts_sf is not None and alerts_sf.suppressed(line, CHECKER):
        continue
      findings.append(Finding(
        CHECKER, "unknown-alert-metric", repo.alerts_path, line, key=f"{kwarg}:{ref}",
        message=f"AlertRule {kwarg}={ref!r} needs exported {want_type} `{want}` "
                "but the extracted metrics surface has no such series — "
                "the rule would evaluate to 'no data' forever",
      ))

  # Engine counters the API exports must be incremented somewhere, and
  # stats-dict rows (pool/host/perf gauges) must read a key some engine
  # code actually produces.
  api_sf = repo.file(repo.api_metrics_path)
  if api_sf is not None and api_sf.tree is not None:
    incremented = _engine_aug_attrs(repo)
    produced = _produced_dict_keys(repo)
    for loop, rows in _tuple_table(api_sf):
      is_counter = (_loop_metric_type(loop) or "counter") == "counter"
      for attr, name, line in rows:
        if attr.startswith("_"):
          if is_counter and attr not in incremented \
              and not api_sf.suppressed(line, CHECKER):
            findings.append(Finding(
              CHECKER, "dead-exported-counter", repo.api_metrics_path, line, key=name,
              message=f"API exports `{name}` from engine attr `{attr}` but nothing "
                      "in the tree increments that attr — stale exposition row",
            ))
        elif attr not in produced and not api_sf.suppressed(line, CHECKER):
          findings.append(Finding(
            CHECKER, "dead-exported-gauge", repo.api_metrics_path, line, key=name,
            message=f"API exports `{name}` from stats key `{attr!s}` but no engine "
                    "code produces that dict key — the exported series can never "
                    "carry a real value",
          ))
  return findings
