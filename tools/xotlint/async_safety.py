"""async-safety checker: the event loop must never block.

The ring runtime's liveness model makes a blocked loop indistinguishable
from a dead peer (the stall watchdog and health monitor both run ON the
loop), so three classes of finding:

- `blocking-call`: a known-blocking call lexically inside `async def`
  (`time.sleep`, sync HTTP, `subprocess.*`, `.block_until_ready()`,
  `open()` file I/O). Sync helpers *called from* async code are out of
  scope — route real work through an executor and the call site is clean.
- `lock-across-await`: a synchronous (threading) lock held across an
  `await` — the loop parks with the lock taken and every executor thread
  contending on it deadlocks the process.
- `raw-create-task`: `asyncio.create_task` / `ensure_future` outside the
  strong-ref wrapper (`utils/helpers.py` `spawn_detached`). The loop keeps
  only weak refs to tasks: a fire-and-forget task can be GC'd mid-flight
  and its exception silently lost.
"""
from __future__ import annotations

import ast
from typing import List

from tools.xotlint.core import Finding, Repo, dotted_name

CHECKER = "async-safety"

# Dotted-call names that block the calling thread. Matched against the
# resolved attribute chain, so aliasing (`import time as t`) escapes the
# net — acceptable for a repo-native linter that also bans the alias idiom
# in review.
_BLOCKING_CALLS = {
  "time.sleep",
  "subprocess.run", "subprocess.call", "subprocess.check_call",
  "subprocess.check_output", "subprocess.Popen",
  "os.system", "os.waitpid",
  "requests.get", "requests.post", "requests.put", "requests.delete",
  "requests.head", "requests.patch", "requests.request",
  "urllib.request.urlopen",
  "socket.create_connection", "socket.getaddrinfo", "socket.gethostbyname",
}

# Attribute-only patterns: blocking regardless of receiver.
_BLOCKING_ATTRS = {"block_until_ready"}

# Names that mark a context-manager expression as a synchronous lock.
_LOCKY = ("lock", "mutex", "cond", "sema")


def _is_lock_expr(node: ast.AST) -> bool:
  name = dotted_name(node)
  if not name and isinstance(node, ast.Call):
    name = dotted_name(node.func)
  tail = name.rsplit(".", 1)[-1].lower()
  return any(tok in tail for tok in _LOCKY)


class _AsyncVisitor(ast.NodeVisitor):
  def __init__(self, sf, findings: List[Finding]):
    self.sf = sf
    self.findings = findings
    self.async_depth = 0
    self.func_stack: List[str] = []

  # --- scope tracking ---------------------------------------------------

  def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
    self.func_stack.append(node.name)
    prev, self.async_depth = self.async_depth, 0  # sync body: loop not implied
    self.generic_visit(node)
    self.async_depth = prev
    self.func_stack.pop()

  def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
    self.func_stack.append(node.name)
    self.async_depth += 1
    self.generic_visit(node)
    self.async_depth -= 1
    self.func_stack.pop()

  def visit_Lambda(self, node: ast.Lambda) -> None:
    prev, self.async_depth = self.async_depth, 0
    self.generic_visit(node)
    self.async_depth = prev

  # --- findings ---------------------------------------------------------

  def _emit(self, code: str, node: ast.AST, message: str, key: str) -> None:
    if self.sf.suppressed(node.lineno, CHECKER):
      return
    self.findings.append(Finding(
      checker=CHECKER, code=code, path=self.sf.relpath, line=node.lineno,
      message=message, key=key,
    ))

  def _scope(self) -> str:
    return ".".join(self.func_stack) or "<module>"

  def visit_Call(self, node: ast.Call) -> None:
    name = dotted_name(node.func)
    in_wrapper = self.sf.relpath.endswith("utils/helpers.py")
    if name.endswith(("create_task", "ensure_future")) and not in_wrapper \
        and (name.startswith("asyncio.") or ".loop." in f".{name}" or name.startswith("loop.")):
      self._emit(
        "raw-create-task", node,
        f"raw `{name}` — route through utils.helpers.spawn_detached so the task "
        "holds a strong ref and its exception is logged, never silently dropped",
        key=f"{self._scope()}:{name.rsplit('.', 1)[-1]}",
      )
    if self.async_depth > 0:
      blocking = name in _BLOCKING_CALLS
      attr = name.rsplit(".", 1)[-1] if name else (
        node.func.attr if isinstance(node.func, ast.Attribute) else "")
      if not blocking and attr in _BLOCKING_ATTRS:
        blocking, name = True, attr
      if not blocking and name == "open":
        blocking = True
        name = "open"
      if blocking:
        self._emit(
          "blocking-call", node,
          f"blocking `{name}(...)` inside `async def {self._scope()}` — the event "
          "loop (and every watchdog on it) stalls; use the async equivalent or "
          "run it in an executor",
          key=f"{self._scope()}:{name}",
        )
    self.generic_visit(node)

  def visit_With(self, node: ast.With) -> None:
    if self.async_depth > 0 and any(_is_lock_expr(item.context_expr) for item in node.items):
      if any(isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith))
             for child in node.body for n in ast.walk(child)):
        self._emit(
          "lock-across-await", node,
          f"synchronous lock held across `await` in `async def {self._scope()}` — "
          "the loop parks holding the lock; use asyncio.Lock or release before awaiting",
          key=self._scope(),
        )
    self.generic_visit(node)


def check(repo: Repo) -> List[Finding]:
  findings: List[Finding] = []
  for sf in repo.files():
    if sf.tree is None:
      continue
    _AsyncVisitor(sf, findings).visit(sf.tree)
  return findings
