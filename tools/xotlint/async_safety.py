"""async-safety checker: the event loop must never block.

The ring runtime's liveness model makes a blocked loop indistinguishable
from a dead peer (the stall watchdog and health monitor both run ON the
loop), so three classes of finding:

- `blocking-call`: a known-blocking call lexically inside `async def`
  (`time.sleep`, sync HTTP, `subprocess.*`, `.block_until_ready()`,
  `open()` file I/O). Sync helpers *called from* async code are out of
  scope — route real work through an executor and the call site is clean.
- `lock-across-await`: a synchronous (threading) lock held across an
  `await` — the loop parks with the lock taken and every executor thread
  contending on it deadlocks the process.
- `raw-create-task`: `asyncio.create_task` / `ensure_future` outside the
  strong-ref wrapper (`utils/helpers.py` `spawn_detached`). The loop keeps
  only weak refs to tasks: a fire-and-forget task can be GC'd mid-flight
  and its exception silently lost.
"""
from __future__ import annotations

import ast
from typing import List

from tools.xotlint.core import Finding, Repo, dotted_name

CHECKER = "async-safety"

# Dotted-call names that block the calling thread. Matched against the
# resolved attribute chain, so aliasing (`import time as t`) escapes the
# net — acceptable for a repo-native linter that also bans the alias idiom
# in review.
_BLOCKING_CALLS = {
  "time.sleep",
  "subprocess.run", "subprocess.call", "subprocess.check_call",
  "subprocess.check_output", "subprocess.Popen",
  "os.system", "os.waitpid",
  "requests.get", "requests.post", "requests.put", "requests.delete",
  "requests.head", "requests.patch", "requests.request",
  "urllib.request.urlopen",
  "socket.create_connection", "socket.getaddrinfo", "socket.gethostbyname",
}

# Attribute-only patterns: blocking regardless of receiver.
_BLOCKING_ATTRS = {"block_until_ready"}

# Names that mark a context-manager expression as a synchronous lock.
_LOCKY = ("lock", "mutex", "cond", "sema")


def _is_lock_expr(node: ast.AST) -> bool:
  name = dotted_name(node)
  if not name and isinstance(node, ast.Call):
    name = dotted_name(node.func)
  tail = name.rsplit(".", 1)[-1].lower()
  return any(tok in tail for tok in _LOCKY)


def _in_async_scope(sf, node: ast.AST) -> bool:
  """The node's INNERMOST enclosing function is `async def` (a nested sync
  def or lambda inside an async body does not imply the event loop)."""
  fn = sf.enclosing_func(node)
  return isinstance(fn, ast.AsyncFunctionDef)


def _emit(sf, findings, code: str, node: ast.AST, message: str, key: str) -> None:
  if sf.suppressed(node.lineno, CHECKER):
    return
  findings.append(Finding(
    checker=CHECKER, code=code, path=sf.relpath, line=node.lineno,
    message=message, key=key,
  ))


def check(repo: Repo) -> List[Finding]:
  """Single pass over the shared AST cache: scope questions (innermost
  enclosing function, dotted function-name scope) come pre-answered from
  the per-file index instead of a stateful visitor."""
  findings: List[Finding] = []
  for sf in repo.files():
    if sf.tree is None:
      continue
    in_wrapper = sf.relpath.endswith("utils/helpers.py")
    for node in sf.nodes():
      if isinstance(node, ast.Call):
        scope = sf.func_scope(node)
        name = dotted_name(node.func)
        if name.endswith(("create_task", "ensure_future")) and not in_wrapper \
            and (name.startswith("asyncio.") or ".loop." in f".{name}" or name.startswith("loop.")):
          _emit(
            sf, findings, "raw-create-task", node,
            f"raw `{name}` — route through utils.helpers.spawn_detached so the task "
            "holds a strong ref and its exception is logged, never silently dropped",
            key=f"{scope}:{name.rsplit('.', 1)[-1]}",
          )
        if _in_async_scope(sf, node):
          blocking = name in _BLOCKING_CALLS
          attr = name.rsplit(".", 1)[-1] if name else (
            node.func.attr if isinstance(node.func, ast.Attribute) else "")
          if not blocking and attr in _BLOCKING_ATTRS:
            blocking, name = True, attr
          if not blocking and name == "open":
            blocking = True
            name = "open"
          if blocking:
            _emit(
              sf, findings, "blocking-call", node,
              f"blocking `{name}(...)` inside `async def {scope}` — the event "
              "loop (and every watchdog on it) stalls; use the async equivalent or "
              "run it in an executor",
              key=f"{scope}:{name}",
            )
      elif isinstance(node, ast.With) and _in_async_scope(sf, node) \
          and any(_is_lock_expr(item.context_expr) for item in node.items):
        if any(isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith))
               for child in node.body for n in ast.walk(child)):
          scope = sf.func_scope(node)
          _emit(
            sf, findings, "lock-across-await", node,
            f"synchronous lock held across `await` in `async def {scope}` — "
            "the loop parks holding the lock; use asyncio.Lock or release before awaiting",
            key=scope,
          )
  return findings
