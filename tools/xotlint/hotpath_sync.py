"""hotpath-sync checker: no host sync reachable from the decode/prefill
dispatch entry points.

PR 7-9 prove "zero added host syncs on the decode hot path" dynamically by
monkeypatching `jax.block_until_ready` / `np.asarray` around one driven
request (test_perf_attr / test_alerts). That guards the paths the tests
happen to drive; this checker generalizes it to EVERY function reachable
(callgraph closure) from the declared dispatch entry points, present and
future call sites alike.

Flagged inside reachable functions:

- `np.asarray(...)` / `numpy.asarray(...)` of a device-tainted value (D2H
  fetch — the dominant per-chunk serialization cost);
- `.block_until_ready()`, `jax.device_get(...)`, `jax.device_put(...)`;
- `.item()` / `int(...)` / `float(...)` applied to a device-tainted value
  (each is a hidden blocking transfer).

"Device-tainted" is per-function dataflow: names (dotted targets included)
assigned from jit dispatches / `jnp.*` calls, propagated through
subscripts, method calls, tuple unpacking and reassignment. Host-side
metadata (`np.asarray(page_ids)` on a Python list) is NOT a sync and is
not flagged — the taint gate is what keeps this checker's real-tree run
meaningful rather than a blanket asarray ban.

`SANCTIONED` is the explicit boundary list — (function-qual suffix, op)
pairs where a sync is the DESIGN (the sampling readback that ends a chunk,
the logprob report fetch). It is the single source of truth the dynamic
monkeypatch tests cross-check (tests/test_xotlint.py asserts the two
agree), so the list can't drift from what the runtime actually does.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.xotlint.core import Finding, Repo, dotted_name
from tools.xotlint.callgraph import jit_sites, program

CHECKER = "hotpath-sync"

# Dispatch entry points (suffix-matched against `path::Class.func` quals).
# These are the executor-side bodies the _DecodeBatcher drain loop and the
# ring driver hand to the engine — everything the decode/prefill hot path
# can execute is callgraph-reachable from here.
ENTRY_POINTS = (
  "engine.py::JAXShardInferenceEngine._decode_batch_sync",
  "engine.py::JAXShardInferenceEngine._paged_fill_sync",
  "engine.py::_DecodeBatcher._drain",
  "transformer.py::forward_shard",
)

# (function-qual SUFFIX, op) -> reason. The one list the dynamic
# monkeypatch tests agree with: a sync op at one of these seams is the
# sanctioned host boundary of the hot path, anywhere else it is a finding.
# Kept EXACT: tests assert that clearing this dict makes the checker fire
# precisely these identities on the real tree (no dead sanctioning), and
# that the callers the dynamic sync-count tests observe fall inside it.
SANCTIONED = {
  # Chunk-boundary sampling readback: the ONE fetch per decode chunk that
  # hands sampled tokens to the host (dispatched AFTER the speculative
  # next chunk, so the device keeps computing while the host ingests),
  # plus the spec-next prev-token `int(...)` over the already-fetched
  # host array.
  ("JAXShardInferenceEngine._decode_batch_sync", "np.asarray"):
    "sampling readback: the per-chunk token fetch",
  ("JAXShardInferenceEngine._decode_batch_sync", "int"):
    "spec-next bookkeeping reads the already-fetched host array",
  ("JAXShardInferenceEngine._decode_batch_paged_sync", "np.asarray"):
    "sampling readback on the paged decode path",
  # Page-table placement under a serving mesh: an ASYNC host→device copy
  # of a few KB of metadata, explicitly replicated so paged executables
  # see mesh-consistent input shardings. device_put returns immediately —
  # it is the checker's conservative lumping with device_get that lands
  # it here, not a real sync.
  ("JAXShardInferenceEngine._device_table", "jax.device_put"):
    "async replicated placement of the KB-scale page table on the mesh",
}

_DEVICE_CALL_HEADS = ("jnp", "jax")
_FETCH_ATTRS = {"block_until_ready", "item"}
# jnp/jax calls that return host metadata, not device arrays — these must
# not seed taint (float(jnp.iinfo(dtype).max) is pure host arithmetic).
_METADATA_TAILS = {"iinfo", "finfo", "dtype", "result_type", "ndim", "shape"}
# Attribute reads on a device value that are FREE host metadata, not a
# transfer: `int(x.shape[0])` / `float(x.ndim)` / `len(x)` never sync.
_META_ATTRS = {"shape", "ndim", "dtype", "size"}


def _is_sanctioned(qual: str, op: str) -> Optional[str]:
  scope = qual.split("::", 1)[1]
  for (suffix, s_op), reason in SANCTIONED.items():
    if s_op == op and (scope == suffix or scope.endswith("." + suffix)
                       or qual.endswith("::" + suffix)):
      return reason
  return None


def _value_refs(node: ast.AST) -> Set[str]:
  """Dotted names referenced BY VALUE inside an expression — occurrences
  behind a metadata attribute (`x.shape[0]`, `x.ndim`) or inside `len(x)`
  are free host reads, not array uses, and are excluded."""
  parents = {}
  for n in ast.walk(node):
    for c in ast.iter_child_nodes(n):
      parents[id(c)] = n
  out: Set[str] = set()
  for n in ast.walk(node):
    if isinstance(n, (ast.Name, ast.Attribute)):
      d = dotted_name(n)
      if not d:
        continue
      p = parents.get(id(n))
      if isinstance(p, ast.Attribute) and p.attr in _META_ATTRS:
        continue
      if isinstance(p, ast.Call) and isinstance(p.func, ast.Name) \
          and p.func.id == "len" and n in p.args:
        continue
      out.add(d)
  return out


class _Taint:
  """Per-function device-value taint: which dotted names hold (or contain)
  device arrays. Seeded by assignments from jit/jnp calls, propagated
  through any expression that mentions a tainted name."""

  def __init__(self, func: ast.AST, jit_names: Set[str]):
    self.tainted: Set[str] = set()
    self.jit_names = jit_names
    changed = True
    rounds = 0
    while changed and rounds < 4:  # tiny fixpoint; functions are short
      changed = self._pass(func)
      rounds += 1

  def _device_expr(self, node: ast.AST) -> bool:
    for n in ast.walk(node):
      if isinstance(n, ast.Call):
        d = dotted_name(n.func)
        if d:
          head = d.split(".", 1)[0]
          tail = d.rsplit(".", 1)[-1]
          if head in _DEVICE_CALL_HEADS and tail not in _METADATA_TAILS:
            return True
          if tail in self.jit_names:
            return True
    return bool(_value_refs(node) & self.tainted)

  def _taint_target(self, tgt: ast.AST) -> bool:
    changed = False
    if isinstance(tgt, (ast.Tuple, ast.List)):
      for e in tgt.elts:
        changed |= self._taint_target(e)
      return changed
    d = dotted_name(tgt)
    if d and d not in self.tainted:
      self.tainted.add(d)
      return True
    return changed

  def _pass(self, func: ast.AST) -> bool:
    changed = False
    for node in ast.walk(func):
      if isinstance(node, ast.Assign) and self._device_expr(node.value):
        for t in node.targets:
          changed |= self._taint_target(t)
      elif isinstance(node, ast.AugAssign) and self._device_expr(node.value):
        changed |= self._taint_target(node.target)
    return changed

  def hits(self, node: ast.AST) -> bool:
    # By-value tainted references OR a direct device-producing call inside
    # the expression (np.asarray(decode_chunk(...)[0])) count; metadata
    # reads of tainted values (.shape/.ndim/len) do not.
    return self._device_expr(node)


def check(repo: Repo) -> List[Finding]:
  prog = program(repo)
  jits = {s.name for s in jit_sites(repo)}
  # Jitted-callable ATTRIBUTE names (ctx.forward_jit, fill_jits[...]) and
  # decorated functions both dispatch on call — their results are device.
  reach = prog.reachable(ENTRY_POINTS)
  findings: List[Finding] = []
  seen: Set[str] = set()
  for qual, chain in sorted(reach.items()):
    info = prog.funcs.get(qual)
    if info is None:
      continue
    sf = info.sf
    scope_node = info.node
    taint = _Taint(scope_node, jits)
    for node in ast.walk(scope_node):
      if not isinstance(node, ast.Call):
        continue
      d = dotted_name(node.func)
      op = None
      tainted_arg = node.args and taint.hits(node.args[0])
      if d in ("np.asarray", "numpy.asarray") and tainted_arg:
        op = "np.asarray"
      elif d in ("jax.device_get", "jax.device_put"):
        op = d
      elif d in ("int", "float") and tainted_arg:
        op = d
      elif isinstance(node.func, ast.Attribute) and node.func.attr in _FETCH_ATTRS:
        if node.func.attr == "block_until_ready" or taint.hits(node.func.value):
          op = node.func.attr
      if op is None:
        continue
      if _is_sanctioned(qual, op) is not None:
        continue
      if sf.suppressed(node.lineno, CHECKER):
        continue
      key = f"{sf.func_scope(node)}:{op}"
      ident = f"{sf.relpath}:{key}"
      if ident in seen:
        continue  # one finding per (function, op): line-free identity
      seen.add(ident)
      witness = " -> ".join(q.split("::", 1)[1] for q in chain[-3:])
      findings.append(Finding(
        checker=CHECKER, code="host-sync-on-hot-path", path=sf.relpath,
        line=node.lineno, key=key,
        message=f"host sync `{op}` reachable from the dispatch hot path "
                f"(via {witness}) — move it behind the sanctioned boundary "
                "(sampling readback / _observe_dispatch) or off the path; "
                "see tools/xotlint/hotpath_sync.py SANCTIONED",
      ))
  return findings
