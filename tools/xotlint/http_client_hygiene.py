"""http-client-hygiene checker: cross-process HTTP calls degrade, not crash.

The fabric/router guarantee is "always degrade to cold forward": a peer
that is down, slow, or mid-respawn must cost a miss, never an unbounded
hang or an unhandled exception on a serving path. Statically enforced on
every transport site the wire model (wire.py) found:

- **missing-timeout**: the call can hang forever. urllib `urlopen` must
  carry `timeout=` at the call; an aiohttp session call must carry a
  per-call `timeout=` UNLESS every `ClientSession(...)` constructed in
  the same module carries a session-level timeout (then per-call
  timeouts are redundant by construction).
- **uncontained-call**: no `try`/`except` stands between the call and its
  entry point. Containment may live in the caller (a transport helper
  whose every call site is wrapped) — the check walks in-repo call sites
  (including function references handed to executors) up to three hops.
  A deliberate fire-and-forget whose failure is consumed elsewhere
  (e.g. `task.exception()`) earns an inline suppression with its reason.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.xotlint.core import Finding, Repo, SourceFile, dotted_name
from tools.xotlint.wire import WireModel, wire_model

CHECKER = "http-client-hygiene"

_FUNC = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _in_try(sf: SourceFile, node: ast.AST) -> bool:
  """The node sits in the BODY of a try with at least one except handler,
  within its own function (a finally-only try contains nothing)."""
  child: ast.AST = node
  parent = sf.parent(child)
  while parent is not None and not isinstance(parent, _FUNC):
    if isinstance(parent, ast.Try) and parent.handlers \
        and any(child is stmt for stmt in parent.body):
      return True
    child = parent
    parent = sf.parent(parent)
  return False


def _caller_index(wm: WireModel) -> Dict[str, List[Tuple[SourceFile, ast.AST, bool]]]:
  """Bare function name -> (file, call site, via-attribute) across the
  scanned tree. References in argument position count
  (`run_in_executor(None, post)`, `spawn_detached(self._open_attempt(...))`
  both reach the body). The via-attribute flag lets _contained ignore
  `session.post(...)` when resolving a PLAIN function named `post` — an
  attribute call targets another object's method, never a local def."""
  idx: Dict[str, List[Tuple[SourceFile, ast.AST, bool]]] = {}
  for sf in wm.files:
    for node in sf.nodes():
      if not isinstance(node, ast.Call):
        continue
      name = dotted_name(node.func)
      if name:
        idx.setdefault(name.rsplit(".", 1)[-1], []).append(
          (sf, node, isinstance(node.func, ast.Attribute)))
      for arg in list(node.args) + [kw.value for kw in node.keywords]:
        rname = dotted_name(arg)
        if rname:
          idx.setdefault(rname.rsplit(".", 1)[-1], []).append(
            (sf, node, isinstance(arg, ast.Attribute)))
  return idx


def _contained(wm: WireModel, idx, sf: SourceFile, node: ast.AST,
               seen: Set[Tuple[str, str]], done: Dict[Tuple[str, str], bool],
               depth: int = 0) -> bool:
  if _in_try(sf, node):
    return True
  if depth >= 3:
    return False
  fn = sf.enclosing_func(node)
  if fn is None or isinstance(fn, ast.Lambda):
    return False  # module level / lambda: nothing upstream can be credited
  key = (sf.relpath, sf.qual(fn))
  if key in done:
    # Two call sites climbing to the same function share its verdict
    # (three `_chat_once(...)` calls all resolve through `run_soak`).
    return done[key]
  if key in seen:
    return False  # recursion cycle: nothing upstream resolved yet
  seen.add(key)
  # A plain (non-method) function is only ever called/referenced by bare
  # name; attribute sites (`session.post`) are some OTHER object's method.
  is_method = sf.class_scope(fn) is not None
  sites = [(s, n) for s, n, via_attr in idx.get(fn.name, [])
           if is_method or not via_attr]
  # Prefer same-file call sites: cross-file name collisions (two CLIs each
  # defining `_fetch`) must not let one file's wrapping excuse the other's.
  local = [(s, n) for s, n in sites if s is sf]
  sites = local or sites
  # Exclude recursive self-references from within the function itself.
  sites = [(s, n) for s, n in sites
           if not (s is sf and sf.enclosing_func(n) is fn)]
  verdict = bool(sites) and \
      all(_contained(wm, idx, s, n, seen, done, depth + 1) for s, n in sites)
  done[key] = verdict
  seen.discard(key)
  return verdict


def check(repo: Repo) -> List[Finding]:
  wm = wire_model(repo)
  findings: List[Finding] = []
  seen_ids: set = set()
  idx: Optional[dict] = None

  def emit(f: Finding, sf: SourceFile, line: int) -> None:
    if f.identity not in seen_ids and not sf.suppressed(line, CHECKER):
      seen_ids.add(f.identity)
      findings.append(f)

  for t in wm.transports:
    where = t.path or "dynamic-url"
    if not t.has_timeout and not (
        t.kind == "session" and wm.session_module_timeout.get(t.sf.relpath)):
      hint = ("pass `timeout=` to the call" if t.kind == "urllib" else
              "pass `timeout=` here or construct every ClientSession in "
              "this module with a session-level timeout")
      emit(Finding(
        CHECKER, "missing-timeout", t.sf.relpath, t.line,
        key=f"{t.scope}:{where}",
        message=f"cross-process `{t.kind}` call to `{where}` has no timeout "
                f"and can hang forever — {hint}",
      ), t.sf, t.line)
    if idx is None:
      idx = _caller_index(wm)
    if not _contained(wm, idx, t.sf, t.call, set(), {}):
      emit(Finding(
        CHECKER, "uncontained-call", t.sf.relpath, t.line,
        key=f"{t.scope}:{where}",
        message=f"cross-process `{t.kind}` call to `{where}` has no "
                "try/except between it and its entry point (checked three "
                "caller hops) — a dead peer must degrade, not raise; wrap "
                "it, or suppress with the reason failures are consumed "
                "elsewhere",
      ), t.sf, t.line)
  return findings
