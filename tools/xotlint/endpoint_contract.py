"""endpoint-contract checker: client URLs and registered routes agree.

Both directions of the HTTP seam, from the shared wire model (wire.py):

- **unknown-route**: a client builds a URL (transport call or loose
  f-string/literal) whose path+method matches no registered route — a
  typo'd path, a stale client after a route rename, or a route that was
  never wired. Only in-scope paths are checked (`/v1/...` or an exact
  registered path), so external URLs (HuggingFace downloads) never match.
- **dead-route**: a registered route no in-repo client references.
  `ALLOWLIST` is the explicit external surface — OpenAI-compatible
  endpoints, the tinychat UI's fetches, operator/debug endpoints driven
  by curl — kept EXACT: tests assert that clearing it makes the checker
  fire precisely these identities on the real tree (no dead allowlisting).

Also owns the generated README "HTTP API reference" section
(`python -m tools.xotlint --endpoint-docs`, BEGIN/END markers like the
knob table) and its drift findings: missing/stale/phantom rows fail CI
with a per-route message instead of a wall of diff.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from tools.xotlint.core import Finding, Repo
from tools.xotlint.wire import path_match, wire_model

CHECKER = "endpoint-contract"

BEGIN_MARK = "<!-- BEGIN XOT HTTP API (generated: python -m tools.xotlint --endpoint-docs) -->"
END_MARK = "<!-- END XOT HTTP API -->"

_ROW_RE = re.compile(
  r"^\|\s*`(GET|POST|DELETE|PUT)`\s*\|\s*`([^`]+)`\s*\|\s*`([^`]+)`\s*\|\s*`([^`]+)`\s*\|$")

# (method, path) -> why this route is legitimately consumed by nothing in
# the repo: the OpenAI-compatible API surface, the tinychat UI's fetch()
# calls (tinychat/index.html is not Python, so the extractor cannot see
# them), and operator endpoints driven by curl/browser. Kept exact — the
# sanctioned-list cross-check test clears this dict and asserts the
# checker fires precisely these identities on the real tree.
ALLOWLIST: Dict[Tuple[str, str], str] = {
  ("POST", "/chat/completions"): "OpenAI-compat alias (clients hit /v1/...)",
  ("POST", "/v1/chat/token/encode"): "tinychat UI fetch (index.html)",
  ("POST", "/chat/token/encode"): "OpenAI-compat alias of the above",
  ("GET", "/topology"): "un-versioned alias for external dashboards",
  ("GET", "/v1/download/progress"): "tinychat UI download progress poll",
  ("DELETE", "/models/{model_name}"): "un-versioned alias (curl surface)",
  ("DELETE", "/v1/models/{model_name}"): "tinychat UI model delete",
  ("POST", "/download"): "un-versioned alias (curl surface)",
  ("POST", "/v1/download"): "tinychat UI model download",
  ("GET", "/initial_models"): "tinychat UI boot fetch",
  ("GET", "/quit"): "operator curl shutdown",
  ("POST", "/quit"): "reference parity verb for /quit",
  ("POST", "/v1/image/generations"): "OpenAI-compat image surface",
  ("POST", "/v1/trace/device/start"): "operator curl (device profiler)",
  ("POST", "/v1/trace/device/stop"): "operator curl (device profiler)",
  ("GET", "/"): "browser landing page (tinychat)",
}


def _doc_rows(repo: Repo) -> List[Tuple[str, str, str, str]]:
  wm = wire_model(repo)
  rows = set()
  for r in wm.routes:
    handler = r.handler[5:] if r.handler.startswith("self.") else r.handler
    rows.add((r.path, r.method, r.sf.relpath, handler))
  return [(m, p, s, h) for (p, m, s, h) in sorted(rows)]


def generated_section(repo: Repo) -> str:
  """The full replacement text between (and including) the markers."""
  lines = [BEGIN_MARK, "",
           "| Method | Path | Surface | Handler |",
           "|---|---|---|---|"]
  for method, path, surface, handler in _doc_rows(repo):
    lines.append(f"| `{method}` | `{path}` | `{surface}` | `{handler}` |")
  lines.append("")
  lines.append(END_MARK)
  return "\n".join(lines)


def _parse_rows(section: str) -> Dict[Tuple[str, str], Tuple[str, str]]:
  rows: Dict[Tuple[str, str], Tuple[str, str]] = {}
  for line in section.splitlines():
    m = _ROW_RE.match(line.strip())
    if m:
      rows[(m.group(1), m.group(2))] = (m.group(3), m.group(4))
  return rows


def _find_section(text: str) -> Optional[str]:
  start = text.find(BEGIN_MARK)
  end = text.find(END_MARK)
  if start < 0 or end < 0 or end < start:
    return None
  return text[start:end + len(END_MARK)]


def _doc_findings(repo: Repo) -> List[Finding]:
  wm = wire_model(repo)
  if not wm.routes:
    return []  # no HTTP surface (fixture trees) -> nothing to document
  readme = repo.read_text(repo.readme_path)
  if readme is None:
    return []  # doc-drift already reports the missing README
  section = _find_section(readme)
  if section is None:
    return [Finding(
      CHECKER, "missing-api-section", repo.readme_path, 1,
      f"{repo.readme_path} has no `{BEGIN_MARK}` ... `{END_MARK}` block — "
      "add one and fill it with `python -m tools.xotlint --endpoint-docs`",
      key="section",
    )]
  documented = _parse_rows(section)
  expected = _parse_rows(generated_section(repo))
  findings: List[Finding] = []
  line_of = {key: i + 1 for i, line in enumerate(readme.splitlines())
             for key in documented if f"`{key[0]}` | `{key[1]}`" in line}
  for key, row in expected.items():
    if key not in documented:
      findings.append(Finding(
        CHECKER, "undocumented-route", repo.readme_path, 1,
        key=f"{key[0]} {key[1]}",
        message=f"route `{key[0]} {key[1]}` is registered but missing from the "
                "README HTTP API table — regenerate with "
                "`python -m tools.xotlint --endpoint-docs`",
      ))
    elif documented[key] != row:
      findings.append(Finding(
        CHECKER, "stale-api-doc", repo.readme_path, line_of.get(key, 1),
        key=f"{key[0]} {key[1]}",
        message=f"README row for `{key[0]} {key[1]}` (surface/handler) differs "
                "from the registration — regenerate with "
                "`python -m tools.xotlint --endpoint-docs`",
      ))
  for key in documented:
    if key not in expected:
      findings.append(Finding(
        CHECKER, "phantom-route-doc", repo.readme_path, line_of.get(key, 1),
        key=f"{key[0]} {key[1]}",
        message=f"README documents `{key[0]} {key[1]}` but no such route is "
                "registered — remove the row or register the route",
      ))
  return findings


def check(repo: Repo) -> List[Finding]:
  wm = wire_model(repo)
  findings: List[Finding] = []
  seen: set = set()

  # Client -> server: every in-scope client path must hit a real route.
  for ref in wm.client_refs:
    if wm.routes_matching(ref.path, ref.method):
      continue
    if wm.routes_matching(ref.path):
      # Path exists but under a different verb: name the verb mismatch.
      msg = (f"client calls `{ref.method} {ref.path}` but the route is "
             f"registered under a different method")
    else:
      msg = (f"client references `{ref.path}` but no route registers it — "
             "typo'd path, or the server side was never wired")
    f = Finding(CHECKER, "unknown-route", ref.sf.relpath, ref.line,
                key=f"{ref.method or 'ANY'} {ref.path}", message=msg)
    if f.identity in seen or ref.sf.suppressed(ref.line, CHECKER):
      continue
    seen.add(f.identity)
    findings.append(f)

  # Server -> client: a route nothing in the repo consumes is dead surface
  # unless the allowlist names its external consumer.
  for route in wm.routes:
    consumed = any(path_ok for path_ok in (
      (path_match(ref.path, route.path) and
       (ref.method is None or ref.method == route.method))
      for ref in wm.client_refs))
    if consumed:
      continue
    if (route.method, route.path) in ALLOWLIST:
      continue
    f = Finding(
      CHECKER, "dead-route", route.sf.relpath, route.line,
      key=f"{route.method} {route.path}",
      message=f"route `{route.method} {route.path}` has no in-repo consumer "
              "and is not in the external-surface ALLOWLIST — delete the "
              "route or add it to tools/xotlint/endpoint_contract.py with "
              "its external consumer",
    )
    if f.identity in seen or route.sf.suppressed(route.line, CHECKER):
      continue
    seen.add(f.identity)
    findings.append(f)

  findings.extend(_doc_findings(repo))
  return findings
