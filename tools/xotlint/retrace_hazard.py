"""retrace-hazard checker: every `jax.jit` site keeps its executable count
bounded.

A jit executable is keyed on (static arg VALUES, traced arg SHAPES,
closure constants). Three ways the key-space silently explodes — each one
a compile storm mid-serving that the flight recorder only reports after
the fact (`xot_jit_first_dispatch_total`):

- `unbounded-static`: a static argname that carries a raw position /
  offset / count. One compile per distinct value; positions are unbounded.
  Chunk sizes riding the power-of-two ladder (`num_tokens`), sampling
  constants (`top_k`/`top_p`), block sizes and flags are BOUNDED by
  design and allowlisted below.
- `traced-branch`: a Python `if`/`while` on a TRACED parameter inside a
  jitted function — a TracerBoolConversionError at best, a silent
  concretization (one compile per value) under `static_argnums` drift at
  worst. Branching on `.shape`/`.ndim`/`.dtype` or on `is None` is static
  structure and fine.
- `mutable-capture`: a jitted function closing over a module-level
  list/dict/set. Mutation invalidates nothing (jit hashes by identity or
  not at all) — stale constants or unhashable errors at dispatch.
"""
from __future__ import annotations

import ast
import re
from typing import List, Set

from tools.xotlint.core import Finding, Repo, dotted_name
from tools.xotlint.callgraph import jit_sites

CHECKER = "retrace-hazard"

# Static argnames that smell like per-request positions/offsets (one
# executable per VALUE).
_UNBOUNDED_RE = re.compile(
  r"(^|_)(pos|position|start|offset|index|idx|seq_len|cache_len|length)(_|$)")

# Bounded-by-design statics the real tree justifies: chunk sizes ride the
# power-of-two ladder, sampling constants come from a bounded request
# vocabulary, block/layer constants are config.
BOUNDED_STATIC_OK = {
  "num_tokens", "top_k", "top_p", "top_lp", "n", "page", "n_segs",
  "pad_rows", "block_q", "block_k", "block_out", "interpret", "variant",
  "softcap", "cfg", "is_first", "is_last", "use_flash", "use_flash_decode",
  "use_kernel", "moe_routed", "paged_kernel", "start_layer", "start_layers",
}

_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}


def _names_in(node: ast.AST) -> Set[str]:
  return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _test_is_static(test: ast.AST, traced: Set[str]) -> bool:
  """True when the branch condition only consults static structure of
  traced values: `.shape`/`.ndim`/`.dtype` access, `is (not) None`,
  `isinstance(x, ...)` Python-type tests, or no traced name at all."""
  if not (_names_in(test) & traced):
    return True
  if isinstance(test, ast.Compare) and all(
      isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
    return True
  if isinstance(test, ast.BoolOp):
    remaining = set(traced)
    for v in test.values:
      if not _test_is_static(v, remaining):
        return False
      if isinstance(test.op, ast.And):
        # `isinstance(x, (int, float)) and x == 0.0` is the static-shortcut
        # idiom: the guard short-circuits for tracers, so later operands
        # only ever see a host scalar.
        for n in ast.walk(v):
          if isinstance(n, ast.Call) and dotted_name(n.func) == "isinstance" \
              and n.args and isinstance(n.args[0], ast.Name):
            remaining.discard(n.args[0].id)
    return True
  if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
    return _test_is_static(test.operand, traced)
  # Every traced-name occurrence must be behind a metadata attribute or an
  # isinstance() type probe.
  parents = {}
  for n in ast.walk(test):
    for c in ast.iter_child_nodes(n):
      parents[id(c)] = n
  for n in ast.walk(test):
    if isinstance(n, ast.Name) and n.id in traced:
      p = parents.get(id(n))
      if isinstance(p, ast.Attribute) and p.attr in _SHAPE_ATTRS:
        continue
      if isinstance(p, ast.Call) and dotted_name(p.func) == "isinstance":
        continue
      return False
  return True


def _module_mutables(sf) -> Set[str]:
  """Module-level names bound to list/dict/set displays (or their
  constructors) — the mutable-capture candidates."""
  out: Set[str] = set()
  if sf.tree is None:
    return out
  for stmt in sf.tree.body:
    if isinstance(stmt, ast.Assign):
      v = stmt.value
      mutable = isinstance(v, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                               ast.DictComp, ast.SetComp)) or (
        isinstance(v, ast.Call) and dotted_name(v.func) in ("list", "dict", "set"))
      if mutable:
        for t in stmt.targets:
          if isinstance(t, ast.Name):
            out.add(t.id)
  return out


def check(repo: Repo) -> List[Finding]:
  findings: List[Finding] = []
  for site in jit_sites(repo):
    sf = site.sf

    for name in site.static_names:
      if name in BOUNDED_STATIC_OK or not _UNBOUNDED_RE.search(name):
        continue
      if sf.suppressed(site.line, CHECKER):
        continue
      findings.append(Finding(
        checker=CHECKER, code="unbounded-static", path=sf.relpath,
        line=site.line, key=f"{site.name}:{name}",
        message=f"static argname `{name}` on jit of `{site.name}` looks like "
                "a raw position/offset — one executable per distinct value "
                "(compile storm); trace it (dynamic_slice) or bound it to the "
                "power-of-two ladder and allowlist it in retrace_hazard.py",
      ))

    fn = site.func_node
    if fn is None:
      continue
    params = set(site.params)
    traced = params - set(site.static_names)
    # Locals assigned inside shadow params for branching purposes only when
    # reassigned from host values — keep it simple: params only.
    for node in ast.walk(fn):
      if isinstance(node, (ast.If, ast.While)) and not _test_is_static(node.test, traced):
        if sf.suppressed(node.lineno, CHECKER):
          continue
        findings.append(Finding(
          checker=CHECKER, code="traced-branch", path=sf.relpath,
          line=node.lineno, key=f"{site.name}:{sf.func_scope(node)}",
          message=f"Python branch on traced value inside jitted `{site.name}` "
                  "— TracerBoolConversionError at trace time (or a silent "
                  "per-value recompile); use jnp.where/lax.cond or make the "
                  "operand static",
        ))

    mutables = _module_mutables(sf)
    if mutables:
      local: Set[str] = set(params)
      for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
          for t in node.targets:
            local |= _names_in(t)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
          local.add(node.name)
      free = {n for n in _names_in(fn) if n in mutables and n not in local}
      for name in sorted(free):
        # Anchor the finding (and its suppression comment) on the first USE
        # of the captured name, not the def line.
        line = min((n.lineno for n in ast.walk(fn)
                    if isinstance(n, ast.Name) and n.id == name), default=fn.lineno)
        if sf.suppressed(line, CHECKER):
          continue
        findings.append(Finding(
          checker=CHECKER, code="mutable-capture", path=sf.relpath,
          line=line, key=f"{site.name}:{name}",
          message=f"jitted `{site.name}` closes over module-level mutable "
                  f"`{name}` — jit sees a stale snapshot (or an unhashable "
                  "error); pass it as an argument or freeze it",
        ))
  return findings
