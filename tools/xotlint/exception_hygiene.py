"""exception-hygiene checker: no silent `except Exception: pass` on serving paths.

Scope: orchestration/, networking/, api/, utils/ — the paths where a
swallowed exception turns a diagnosable failure into a silent hang or a
quietly-degraded ring. A handler is flagged when it catches `Exception` /
`BaseException` / bare `except:` and its body is nothing but `pass` (or
`...`): no log line, no fallback assignment, no re-raise — the reader (and
the operator) can't distinguish "intentionally ignored, here's why" from
"bug". A DEBUG-gated print, a narrowed exception type, or an inline
`# xotlint: disable=exception-hygiene (reason)` all satisfy it.
"""
from __future__ import annotations

import ast
from typing import List

from tools.xotlint.core import Finding, Repo

CHECKER = "exception-hygiene"

_SCOPES = ("orchestration/", "networking/", "api/", "utils/")
_BROAD = {"Exception", "BaseException"}


def _catches_broad(handler: ast.ExceptHandler) -> bool:
  t = handler.type
  if t is None:
    return True  # bare except
  names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
  for name in names:
    last = name.attr if isinstance(name, ast.Attribute) else (
      name.id if isinstance(name, ast.Name) else "")
    if last in _BROAD:
      return True
  return False


def _body_is_silent(handler: ast.ExceptHandler) -> bool:
  for stmt in handler.body:
    if isinstance(stmt, ast.Pass):
      continue
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
      continue  # docstring / ellipsis
    return False
  return True


def check(repo: Repo) -> List[Finding]:
  """Handlers come from the shared AST cache (document order), scoped by
  the dotted class/def path. The scope anchors baseline identity: an
  unrelated handler added elsewhere in the file must not renumber (and so
  un-grandfather) existing findings. Known residual churn: adding/removing
  a SILENT handler earlier in the same scope still shifts later ordinals —
  acceptable because identical `except Exception: pass` bodies offer
  nothing else to key on, and policy keeps the baseline empty anyway."""
  findings: List[Finding] = []
  for sf in repo.files():
    if sf.tree is None:
      continue
    if not any(f"/{scope}" in f"/{sf.relpath}" for scope in _SCOPES):
      continue
    per_scope: dict = {}
    for node in sf.nodes():
      if not isinstance(node, ast.ExceptHandler):
        continue
      if not (_catches_broad(node) and _body_is_silent(node)):
        continue
      scope = sf.qual(node)
      per_scope[scope] = per_scope.get(scope, 0) + 1
      if sf.suppressed(node.lineno, CHECKER):
        continue
      findings.append(Finding(
        checker=CHECKER, code="swallowed-exception", path=sf.relpath,
        line=node.lineno, key=f"{scope}:{per_scope[scope]}",
        message="`except Exception: pass` with no logged reason — log it "
                "(DEBUG-gated is fine), narrow the type, or add "
                "`# xotlint: disable=exception-hygiene (reason)`",
      ))
  return findings
