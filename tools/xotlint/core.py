"""xotlint core: repo model, findings, suppression comments, baseline.

The linter is AST-based and import-free for the tree it scans (it loads
`xotorch_tpu/utils/knobs.py` standalone — that module imports only the
stdlib — but never imports the package under lint, so a tree with a broken
import still lints).

Finding identity is line-number-free (`checker:code:path:key`) so the
committed baseline doesn't churn when unrelated edits move code. Inline
suppressions use a trailing comment on the offending line:

    risky_call()  # xotlint: disable=async-safety (reason why this is fine)

A suppression must name the checker; a parenthesized reason is convention,
enforced by review rather than the tool.
"""
from __future__ import annotations

import ast
import importlib.util
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

_DISABLE_RE = re.compile(r"#\s*xotlint:\s*disable=([a-z0-9_,-]+)\s*(\([^)]*\))?")


@dataclass(frozen=True)
class Finding:
  checker: str  # e.g. "async-safety"
  code: str     # e.g. "blocking-call"
  path: str     # repo-relative, forward slashes
  line: int     # 1-based; informational only (not part of identity)
  message: str
  key: str      # stable detail (symbol-ish) completing the baseline identity

  @property
  def identity(self) -> str:
    return f"{self.checker}:{self.code}:{self.path}:{self.key}"

  def render(self) -> str:
    return f"{self.path}:{self.line}: [{self.checker}/{self.code}] {self.message}"


class SourceFile:
  def __init__(self, root: str, relpath: str):
    self.relpath = relpath.replace(os.sep, "/")
    self.abspath = os.path.join(root, relpath)
    with open(self.abspath, "r", encoding="utf-8") as f:
      self.text = f.read()
    self.lines = self.text.splitlines()
    self.tree: Optional[ast.AST] = None
    self.parse_error: Optional[SyntaxError] = None
    try:
      self.tree = ast.parse(self.text, filename=self.relpath)
    except SyntaxError as e:
      self.parse_error = e
    # Shared AST cache (built lazily, ONCE per file, by _index): every
    # checker iterates these instead of re-walking the tree.
    self._nodes: Optional[List[ast.AST]] = None
    self._parent: Dict[int, ast.AST] = {}
    self._func: Dict[int, Optional[ast.AST]] = {}
    self._func_names: Dict[int, tuple] = {}
    self._classes: Dict[int, tuple] = {}
    # Suppression bookkeeping: which (line, checker) suppressions actually
    # fired this run — the stale-suppression audit's evidence.
    self.suppression_hits: set = set()

  def line_text(self, line: int) -> str:
    if 1 <= line <= len(self.lines):
      return self.lines[line - 1]
    return ""

  def suppressed(self, line: int, checker: str) -> bool:
    m = _DISABLE_RE.search(self.line_text(line))
    if m is None:
      return False
    names = {n.strip() for n in m.group(1).split(",")}
    hit = checker in names or "all" in names
    if hit:
      self.suppression_hits.add((line, checker if checker in names else "all"))
    return hit

  def suppression_sites(self) -> List[tuple]:
    """Every inline suppression in the file: (line, checker names, has a
    parenthesized reason). The audit's work-list."""
    sites = []
    for i, text in enumerate(self.lines, start=1):
      m = _DISABLE_RE.search(text)
      if m is not None:
        names = tuple(n.strip() for n in m.group(1).split(","))
        sites.append((i, names, bool(m.group(2) and m.group(2).strip("() \t"))))
    return sites

  # ------------------------------------------------------- shared AST cache

  def _index(self) -> None:
    """One walk per file: document-ordered node list plus parent, innermost
    enclosing function (sync/async/lambda), enclosing function-NAME stack
    (functions only — the identity convention checkers key on), and
    enclosing class-name stack. All checkers consume this instead of
    running their own ast.walk per concern."""
    nodes: List[ast.AST] = []
    stack = [(self.tree, None, None, (), ())]
    while stack:
      node, parent, func, fnames, classes = stack.pop()
      nodes.append(node)
      nid = id(node)
      self._parent[nid] = parent
      self._func[nid] = func
      self._func_names[nid] = fnames
      self._classes[nid] = classes
      c_func, c_fnames, c_classes = func, fnames, classes
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        c_func, c_fnames = node, fnames + (node.name,)
      elif isinstance(node, ast.Lambda):
        c_func = node  # sync scope boundary; contributes no name
      elif isinstance(node, ast.ClassDef):
        c_classes = classes + (node.name,)
      for child in reversed(list(ast.iter_child_nodes(node))):
        stack.append((child, node, c_func, c_fnames, c_classes))
    self._nodes = nodes

  def nodes(self) -> List[ast.AST]:
    if self._nodes is None:
      if self.tree is None:
        self._nodes = []
      else:
        self._index()
    return self._nodes

  def parent(self, node: ast.AST) -> Optional[ast.AST]:
    self.nodes()
    return self._parent.get(id(node))

  def enclosing_func(self, node: ast.AST) -> Optional[ast.AST]:
    """Innermost enclosing FunctionDef/AsyncFunctionDef/Lambda — for the
    node ITSELF this is the scope it sits in (a def's enclosing_func is its
    outer function, not itself)."""
    self.nodes()
    return self._func.get(id(node))

  def func_scope(self, node: ast.AST) -> str:
    """Dotted enclosing function names (classes excluded) — the existing
    checkers' identity convention, e.g. `hop` or `outer.inner`."""
    self.nodes()
    return ".".join(self._func_names.get(id(node), ())) or "<module>"

  def class_scope(self, node: ast.AST) -> Optional[str]:
    """Innermost enclosing class name, or None at module level."""
    self.nodes()
    classes = self._classes.get(id(node), ())
    return classes[-1] if classes else None

  def func_scope_at_line(self, line: int) -> str:
    """Dotted function scope covering a LINE (for suppression-audit
    identities, which have no AST node to anchor on)."""
    best: Optional[ast.AST] = None
    for node in self.nodes():
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
          and node.lineno <= line <= (node.end_lineno or node.lineno):
        if best is None or node.lineno >= best.lineno:
          best = node
    return self.qual(best) if best is not None else "<module>"

  def qual(self, node: ast.AST) -> str:
    """Class-qualified dotted path of the scope the node sits in (for a
    def node, include the def itself): `Class.method.inner` / `func`."""
    self.nodes()
    nid = id(node)
    parts = list(self._classes.get(nid, ())) + list(self._func_names.get(nid, ()))
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
      parts.append(node.name)
    return ".".join(parts) or "<module>"


class Repo:
  """The tree under lint plus the well-known paths checkers consult.

  Tests point this at fixture trees; defaults describe the real repo.
  """

  def __init__(
    self,
    root: str,
    py_roots: Sequence[str] = ("xotorch_tpu",),
    knobs_path: str = "xotorch_tpu/utils/knobs.py",
    metrics_path: str = "xotorch_tpu/orchestration/metrics.py",
    api_metrics_path: str = "xotorch_tpu/api/chatgpt_api.py",
    readme_path: str = "README.md",
    helpers_path: str = "xotorch_tpu/utils/helpers.py",
    flight_path: str = "xotorch_tpu/orchestration/flight.py",
    alerts_path: str = "xotorch_tpu/orchestration/alerts.py",
  ):
    self.root = os.path.abspath(root)
    self.py_roots = tuple(py_roots)
    self.knobs_path = knobs_path
    self.metrics_path = metrics_path
    self.api_metrics_path = api_metrics_path
    self.readme_path = readme_path
    self.helpers_path = helpers_path
    self.flight_path = flight_path
    self.alerts_path = alerts_path
    self._files: Optional[List[SourceFile]] = None
    self._by_path: Dict[str, SourceFile] = {}
    self._knobs_module = None

  def files(self) -> List[SourceFile]:
    if self._files is None:
      found: List[SourceFile] = []
      for py_root in self.py_roots:
        base = os.path.join(self.root, py_root)
        for dirpath, dirnames, filenames in os.walk(base):
          dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
          for name in sorted(filenames):
            if name.endswith(".py"):
              rel = os.path.relpath(os.path.join(dirpath, name), self.root)
              found.append(SourceFile(self.root, rel))
      self._files = found
      self._by_path = {f.relpath: f for f in found}
    return self._files

  def file(self, relpath: str) -> Optional[SourceFile]:
    self.files()
    relpath = relpath.replace(os.sep, "/")
    sf = self._by_path.get(relpath)
    if sf is None and os.path.isfile(os.path.join(self.root, relpath)):
      sf = SourceFile(self.root, relpath)
      self._by_path[relpath] = sf
    return sf

  def loaded_files(self) -> List[SourceFile]:
    """Every SourceFile this run touched: the py_roots walk PLUS files
    loaded on demand via `file()` (the wire model pulls in tools/soak
    etc.). The suppression audit iterates this so tool-file suppressions
    rot-check like package ones. Sorted for deterministic output."""
    self.files()
    return sorted(self._by_path.values(), key=lambda sf: sf.relpath)

  def read_text(self, relpath: str) -> Optional[str]:
    path = os.path.join(self.root, relpath)
    if not os.path.isfile(path):
      return None
    with open(path, "r", encoding="utf-8") as f:
      return f.read()

  def knobs_module(self):
    """The knob registry loaded standalone (stdlib-only module, so this
    never imports jax or the rest of the package)."""
    if self._knobs_module is None:
      path = os.path.join(self.root, self.knobs_path)
      spec = importlib.util.spec_from_file_location("_xotlint_knobs", path)
      module = importlib.util.module_from_spec(spec)
      sys.modules[spec.name] = module  # dataclasses resolves __module__ here
      spec.loader.exec_module(module)
      self._knobs_module = module
    return self._knobs_module


def dotted_name(node: ast.AST) -> str:
  """`os.environ.get` for Attribute/Name chains, "" for anything dynamic."""
  parts: List[str] = []
  while isinstance(node, ast.Attribute):
    parts.append(node.attr)
    node = node.value
  if isinstance(node, ast.Name):
    parts.append(node.id)
    return ".".join(reversed(parts))
  return ""


def str_arg(call: ast.Call, index: int = 0) -> Optional[str]:
  if len(call.args) > index and isinstance(call.args[index], ast.Constant) \
      and isinstance(call.args[index].value, str):
    return call.args[index].value
  return None


def load_baseline(path: str) -> List[str]:
  if not os.path.isfile(path):
    return []
  with open(path, "r", encoding="utf-8") as f:
    data = json.load(f)
  return list(data.get("findings", []))


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
  identities = sorted({f.identity for f in findings})
  os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
  with open(path, "w", encoding="utf-8") as f:
    json.dump(
      {
        "comment": "Grandfathered xotlint findings. Entries here do not fail CI; "
                   "fix the code and remove the entry rather than adding new ones.",
        "findings": identities,
      },
      f, indent=2,
    )
    f.write("\n")
