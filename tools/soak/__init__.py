"""SLO soak harness: verdict math for multi-process soak runs.

`python -m tools.soak` spawns a REAL N-process ring (tests/xproc_harness —
the same child-environment contract every cross-process test uses), drives
an open-loop load generator against it (tools/soak/loadgen.py), optionally
injects faults on a wall-clock schedule (tools/soak/orchestrator.py), and
writes a `SOAK_*.json` verdict report. This module holds the PURE parts —
percentile math, client/server reconciliation, false-abort classification,
leak checks, report assembly — so the verdict logic is unit-testable
without spawning a single process, and `tools/benchdiff` can gate
soak-to-soak SLO drift from the same flat metric names.

The three verdict questions (ROADMAP "survivability production defaults"):

1. **Reconciliation** — do the server's `xot_ttft_seconds` /
   `xot_request_seconds` histograms agree with what clients measured? The
   server must never report a percentile ABOVE the client's view (it
   observes a strict subset of each request's wall time), and the gap must
   stay under a tolerance (API/tokenizer/HTTP overhead) — catching
   attribution bugs neither side can see alone.
2. **False aborts** — every watchdog/deadline abort must fall inside an
   active fault window; an abort with no injected fault to blame is the
   false positive that blocks the survivability default flip.
3. **Leaks** — after the load drains, in-flight gauges must return to
   zero, the page pool must stop growing, and the host tier must respect
   its byte budget.
4. **Alerts** — every SLO burn-rate alert FIRING must fall inside an
   active fault window (the alert engine must not page on healthy
   traffic), and the smoke's kill must drive at least one alert through
   fired-then-resolved — the end-to-end proof of the pending -> firing ->
   resolved machine (`summarize_alerts`; asserted by `--smoke`).
"""
from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence

SCHEMA = "xot-soak-v1"

# Histogram families reconciled client-vs-server: the client-side sample
# key each maps to, and the check mode the comparison supports.
#
# - `ttft_seconds` is observed at the SAMPLING node from ITS first touch:
#   it structurally under-counts the client view (origin-side prefill,
#   queueing, HTTP are invisible to the sampler), so only the one-sided
#   invariant holds ring-wide: the server must never report MORE TTFT than
#   clients experienced.
# - `request_seconds` is observed per node; every ring member observes the
#   same request from its own first touch, so the ring-merged distribution
#   is a mixture of views. The ORIGIN (API) node's histogram alone is the
#   apples-to-apples twin of client e2e (first touch ≈ HTTP arrival) and
#   supports the two-sided check — provided the client sample also counts
#   errored requests, because the server family records "any outcome".
# - `token_seconds` is observed at the sampler per appended token; the
#   client sample is the raw inter-chunk gap list of ok STREAMED requests
#   (same per-token shape — a per-request mean would be a different
#   distribution), and the gap additionally contains broadcast, HTTP, and
#   SSE framing, so only the one-sided invariant holds: the server may not
#   report MORE per-token time than clients measured (plus bucket
#   quantization). MEDIAN ONLY: the server histogram also counts tokens of
#   requests the client recorded as ERRORS (a kill window's retry storms),
#   so the tails are structurally incomparable — p50 is robust to that
#   contamination, the upper percentiles are not.
RECONCILE_FAMILIES = (
  ("ttft_seconds", "ttft_s", "one_sided"),
  ("request_seconds", "e2e_s", "two_sided"),
  ("token_seconds", "tpot_s", "one_sided"),
)
QUANTILES = (0.5, 0.95, 0.99)
# Per-family quantile restriction for the reconciliation rows (default:
# all of QUANTILES). See the token_seconds note above.
RECONCILE_QUANTILES = {"token_seconds": (0.5,)}


def percentile(samples: Sequence[float], q: float) -> Optional[float]:
  """Linear-interpolation sample percentile (numpy's default method),
  None on empty input."""
  xs = sorted(float(x) for x in samples)
  if not xs:
    return None
  if len(xs) == 1:
    return xs[0]
  rank = max(0.0, min(1.0, q)) * (len(xs) - 1)
  lo = int(math.floor(rank))
  hi = min(lo + 1, len(xs) - 1)
  frac = rank - lo
  return xs[lo] + (xs[hi] - xs[lo]) * frac


def latency_summary(samples: Sequence[float]) -> Dict[str, Optional[float]]:
  xs = [float(x) for x in samples]
  out: Dict[str, Optional[float]] = {
    f"p{int(q * 100)}": percentile(xs, q) for q in QUANTILES
  }
  out["mean"] = (sum(xs) / len(xs)) if xs else None
  out["count"] = float(len(xs))
  return out


def delta_buckets(final_rows: Iterable, base_rows: Iterable) -> List[list]:
  """Cumulative bucket rows covering only the observations made BETWEEN two
  scrapes (load-window delta: the warmup request and any earlier traffic
  drop out of the reconciliation on both sides)."""
  base = {str(le): float(c) for le, c in (base_rows or [])}
  return [[le, max(0.0, float(c) - base.get(str(le), 0.0))]
          for le, c in (final_rows or [])]


def server_percentiles(nodes_final: Dict[str, dict], nodes_base: Dict[str, dict],
                       family: str, only_node=None) -> Dict[str, Optional[float]]:
  """Load-window percentiles for one histogram family from per-node
  cluster-metrics summaries (bucket counts shipped by NodeMetrics.summary),
  ring-merged or restricted to `only_node` (the origin-view families; a
  str, or a SET of node ids — router runs have one origin per replica).
  Nodes missing from the baseline contribute their full final rows (they
  joined mid-run)."""
  from xotorch_tpu.orchestration.metrics import (
    merge_bucket_rows, quantile_bucket_span, quantile_from_buckets)
  origins = ({only_node} if isinstance(only_node, str)
             else set(only_node) if only_node is not None else None)
  rows_per_node = []
  count = 0.0
  for node_id, summary in nodes_final.items():
    if origins is not None and node_id not in origins:
      continue
    h = summary.get(family) if isinstance(summary, dict) else None
    if not isinstance(h, dict) or not h.get("buckets"):
      continue
    base = ((nodes_base.get(node_id) or {}).get(family) or {}).get("buckets")
    rows = delta_buckets(h["buckets"], base)
    rows_per_node.append(rows)
    if rows:
      count += rows[-1][1]
  if not rows_per_node:
    return {"count": 0.0, **{f"p{int(q * 100)}": None for q in QUANTILES}}
  merged = merge_bucket_rows(rows_per_node)
  out: Dict[str, Optional[float]] = {}
  for q in QUANTILES:
    key = f"p{int(q * 100)}"
    out[key] = quantile_from_buckets(merged, q)
    # The containing bucket's width: the honest bound on how far the
    # interpolated percentile can over-state the true one (reconcile adds
    # it to the server-over tolerance).
    out[f"{key}_bucket_s"] = quantile_bucket_span(merged, q)
  out["count"] = count
  return out


def reconcile(client: Dict[str, dict], server: Dict[str, dict],
              tol_s: float, server_over_tol_s: float = 0.5,
              quantile_overrides: Optional[Dict[str, tuple]] = None) -> Dict[str, dict]:
  """Per-percentile client-vs-server agreement rows.

  Every family enforces the structural invariant: the server may not exceed
  the client view by more than `server_over_tol_s` plus the containing
  bucket's width (`p*_bucket_s` rows from server_percentiles — histogram
  interpolation can over-state the true percentile by up to one bucket;
  the server observes a SUBSET of each request's wall clock, so anything
  beyond that means latency is being attributed to requests that never saw
  it). `two_sided` families additionally bound the client-over-
  server gap by `tol_s` (everything the server cannot see: HTTP,
  tokenization, queue-to-API overhead — a bigger gap means server
  histograms are missing real latency). `one_sided` families (TTFT,
  observed at the sampling node from ITS first touch) legitimately
  under-count by origin-side prefill + queueing, so only the structural
  bound applies.

  A side with no observations (e.g. zero streaming requests -> no client
  TTFT samples) yields ok=None rows: unknowable, not failing.

  `quantile_overrides` narrows a family's checked quantiles for runs whose
  fault schedule makes the tails structurally incomparable — e.g. injected
  ProcessPrompt delays land in the server's TTFT histogram for EVERY
  request, while the client TTFT sample only covers streamed ones, so a
  delay that happens to hit only non-streamed requests puts the 10 s+
  observations on exactly one side (the token_seconds median-only
  precedent, applied per run)."""
  quantiles = {**RECONCILE_QUANTILES, **(quantile_overrides or {})}
  out: Dict[str, dict] = {}
  for family, client_key, mode in RECONCILE_FAMILIES:
    c = client.get(client_key) or {}
    s = server.get(family) or {}
    for q in quantiles.get(family, QUANTILES):
      key = f"p{int(q * 100)}"
      cv, sv = c.get(key), s.get(key)
      row: Dict[str, Any] = {"client_s": cv, "server_s": sv, "mode": mode}
      if cv is None or sv is None or not c.get("count") or not s.get("count"):
        row["ok"] = None
      else:
        quant = s.get(f"{key}_bucket_s") or 0.0
        row["delta_s"] = round(cv - sv, 4)
        ok = sv - cv <= server_over_tol_s + quant
        if mode == "two_sided":
          ok = ok and (cv - sv <= tol_s)
        row["ok"] = ok
      out[f"{client_key[:-2]}_{key}"] = row  # e.g. ttft_p95, e2e_p99
  return out


def alert_rows_of(alerts: Optional[dict]) -> List[dict]:
  """Node-tagged FIRING rows from one /v1/alerts cluster scrape (active +
  recent compacts; pending-only rows never fired and carry nothing to
  classify)."""
  rows: List[dict] = []
  for node_id, node_alerts in ((alerts or {}).get("nodes") or {}).items():
    if not isinstance(node_alerts, dict):
      continue
    for row in (node_alerts.get("active") or []) + (node_alerts.get("recent") or []):
      if row.get("fired_at") is None:
        continue
      rows.append({"node_id": node_id, **row})
  return rows


def alert_row_key(row: dict) -> tuple:
  """One firing's identity across scrapes: the same alert seen active in
  one scrape and resolved in a later one is one firing, not two."""
  return (row.get("node_id"), row.get("rule"), round(float(row["fired_at"]), 1))


def classify_alert_firings(rows: Iterable[dict],
                           fault_windows: Iterable[dict],
                           since: Optional[float] = None) -> Dict[str, Any]:
  """Classify the ring's SLO alert firings against the fault schedule. The
  green bar mirrors the abort rule: every FIRING must fall inside an
  active fault window (an alert with no injected fault to blame means the
  rules page on healthy traffic), and the smoke's kill phase must produce
  at least one fired-then-resolved alert — proof the whole pending ->
  firing -> resolved machine works under a real fault. Duplicate rows
  (the same firing seen across scrapes / in both active and recent) merge
  by identity, preferring the resolved view. `since` (unix seconds) bounds
  the verdict to the MEASURED window: the warmup completion's cold-jit
  compile legitimately blows any sane latency target, and its
  fired-then-resolved rows survive in every node's `recent` list — alerts
  that fired before the load window opened are pre-measurement history,
  not evidence about steady-state traffic."""
  windows = [(float(w["t0"]), float(w["t1"])) for w in fault_windows]
  out_rows: List[dict] = []
  seen: Dict[tuple, dict] = {}
  for row in rows:
    fired = float(row["fired_at"])
    if since is not None and fired < since:
      continue
    key = alert_row_key(row)
    prev = seen.get(key)
    if prev is not None:
      if row.get("resolved_at") is not None and prev.get("resolved_at") is None:
        prev["resolved_at"] = row.get("resolved_at")
      continue
    entry = {
      "node_id": row.get("node_id"), "rule": row.get("rule"),
      "family": row.get("family"), "fired_at": fired,
      "resolved_at": row.get("resolved_at"),
      "suspect": row.get("suspect"), "stage": row.get("stage"),
      "in_fault_window": any(t0 <= fired <= t1 for t0, t1 in windows),
    }
    seen[key] = entry
    out_rows.append(entry)
  outside = [r for r in out_rows if not r["in_fault_window"]]
  fired_resolved = [r for r in out_rows
                    if r["in_fault_window"] and r.get("resolved_at") is not None]
  return {
    "firings": out_rows,
    "outside_fault_windows": len(outside),
    "fired_and_resolved_in_window": len(fired_resolved),
  }


def summarize_alerts(alerts: Optional[dict],
                     fault_windows: Iterable[dict]) -> Dict[str, Any]:
  """classify_alert_firings over a single /v1/alerts scrape. The soak
  orchestrator accumulates rows across its CONTINUOUS scrapes instead —
  an eviction prunes a dead peer's compact from later scrapes, so the
  settle scrape alone could lose a firing that happened on it."""
  return classify_alert_firings(alert_rows_of(alerts), fault_windows)


def is_drift_row(row: dict) -> bool:
  """A perf_drift firing (the chronic sentinel) vs an SLO burn firing.
  Classified separately: the two alert classes have different green bars
  and different benchdiff zero-tolerance keys."""
  return str(row.get("rule") or "").startswith("perf_drift")


def summarize_history(history_by_node: Optional[Dict[str, dict]]) -> Optional[Dict[str, Any]]:
  """The report's metrics-history section from the /v1/history scrapes:
  per-node sample/restart counts and trailing gauge means — the record a
  chronic-rot investigation starts from. None when no node served one."""
  if not history_by_node:
    return None
  nodes = {}
  for node_id, h in sorted(history_by_node.items()):
    if not isinstance(h, dict) or not h.get("enabled"):
      continue
    nodes[node_id] = {
      "samples_total": int(h.get("samples_total") or 0),
      "restarts": int(h.get("restarts") or 0),
      "tiers": h.get("tiers"),
      "trailing": h.get("trailing") or {},
    }
  if not nodes:
    return None
  return {
    "nodes": nodes,
    "samples_total": sum(n["samples_total"] for n in nodes.values()),
    "restarts_total": sum(n["restarts"] for n in nodes.values()),
  }


def summarize_drift(rows: Iterable[dict], fault_windows: Iterable[dict],
                    since: Optional[float] = None,
                    router_status: Optional[dict] = None) -> Dict[str, Any]:
  """The report's chronic-drift section: perf_drift firings classified
  against the fault schedule (same window discipline as the SLO rows —
  a drift firing with no injected fault to blame means the sentinel pages
  on healthy traffic) plus the router's differential-drift naming."""
  out = classify_alert_firings(rows, fault_windows, since=since)
  if router_status is not None:
    out["router_named_total"] = int(router_status.get("drift_named_total") or 0)
    # `drift_last` is stamped (name + evidence) at naming time and
    # survives the clear, so the map's shape never depends on whether the
    # live `drift` name had already been forgotten by scrape time.
    out["router_named"] = {
      name: rep["drift_last"]
      for name, rep in (router_status.get("replicas") or {}).items()
      if rep.get("drift_last")
    }
  return out


def summarize_anatomy(anatomy: Optional[dict]) -> Optional[Dict[str, Any]]:
  """The report's stage-breakdown section from one /v1/anatomy scrape on
  the API node: per-stage mean/percentile contributions plus the
  unattributed share benchdiff zero-tolerance-gates on committed green
  files (a green soak whose breakdowns can't attribute most of the time is
  lying about where it went). None when the node served no anatomy."""
  if not isinstance(anatomy, dict) or not anatomy.get("stages"):
    return None
  stages = anatomy["stages"]
  unattr = stages.get("unattributed") or {}
  return {
    "breakdowns": anatomy.get("breakdowns", 0),
    "stages": stages,
    "unattributed_share_mean": float(unattr.get("share_mean") or 0.0),
  }


def summarize_overload(records: Iterable, abort_events: Iterable[dict],
                       overload_windows: Iterable[dict],
                       server_rejections: float) -> Optional[Dict[str, Any]]:
  """The "rejected, not aborted" overload verdict section. Inside the
  overload windows (offered load deliberately above capacity) the green bar
  is: the admission gate shed load as 429s (>= 1 rejection recorded — an
  overload phase that sheds nothing proves nothing), ZERO watchdog/deadline
  aborts (the exact failure mode PR 8 documented: without admission
  control, overload surfaces as "stalled" aborts), and every admitted
  request completes (client errors are judged by the run-wide
  errors-outside-fault-windows rule — overload is not an excuse window).
  None when the run had no overload phase (pre-router reports)."""
  windows = [(float(w["t0"]), float(w["t1"])) for w in overload_windows]
  if not windows:
    return None

  def in_window(ts: float) -> bool:
    return any(t0 <= ts <= t1 for t0, t1 in windows)

  rejected = [r for r in records if getattr(r, "rejected", False)]
  aborts_in = [dict(ev) for ev in abort_events
               if in_window(float(ev.get("ts") or 0.0))]
  return {
    "windows": [{"t0": t0, "t1": t1} for t0, t1 in windows],
    "client_rejected": len(rejected),
    "client_rejected_in_window": sum(1 for r in rejected if in_window(r.t_submit)),
    "watchdog_aborts_in_window": len(aborts_in),
    "abort_events_in_window": aborts_in,
    "server_admission_rejections": float(server_rejections),
  }


def summarize_router(router_status: Optional[dict], tracking: Optional[dict],
                     expect_drain: bool,
                     baseline: Optional[dict] = None) -> Optional[Dict[str, Any]]:
  """The router/failover verdict section from the final /v1/router scrape
  plus the orchestrator's out-of-rotation tracking. The green bar: when a
  gray failure was injected (`expect_drain`), at least one replica went
  through draining AND was readmitted after the fault cleared, and NO
  request was routed to a replica while it was out of rotation (drained
  replicas keep their inflight streams, new traffic lands elsewhere).
  `baseline` (the /v1/router scrape taken at LOAD START) turns the
  run-lifetime drain/readmit totals into load-window deltas — a boot-time
  or warmup-alert drain that resolved before the measured window must not
  satisfy the injected-fault expectation."""
  if router_status is None:
    return None
  replicas = router_status.get("replicas") or {}

  def delta(key: str) -> int:
    return max(0, int(router_status.get(key) or 0)
               - int((baseline or {}).get(key) or 0))

  def out_count(row: dict) -> int:
    # Banked episodes plus the still-open one (a replica that is STILL out
    # at report time must not hide its in-episode routing).
    n = int(row.get("accum") or 0)
    if row.get("episode_start") is not None:
      n += max(0, int(row.get("episode_last") or row["episode_start"])
               - int(row["episode_start"]))
    return n

  routed_while_out = {name: out_count(row) for name, row in (tracking or {}).items()}
  return {
    "replicas": replicas,
    "drains_total": delta("drains_total"),
    "readmits_total": delta("readmits_total"),
    "proxied_total": int(router_status.get("proxied_total") or 0),
    "no_replica_503_total": int(router_status.get("no_replica_503_total") or 0),
    "prefetch_announced_total": int(router_status.get("prefetch_announced_total") or 0),
    "routed_while_out": routed_while_out,
    "expect_drain": bool(expect_drain),
  }


def summarize_fleet(statuses: Optional[Dict[str, dict]],
                    baselines: Optional[Dict[str, dict]],
                    load_router: Optional[dict],
                    load_baseline: Optional[dict],
                    holders: Optional[Iterable[str]] = None,
                    expect: Optional[Dict[str, bool]] = None) -> Dict[str, Any]:
  """The elastic-fleet verdict section. Controller counters are summed
  across routers as load-window deltas — each actuation happens on exactly
  one lease holder, and a since-killed router contributes through its
  last-good scrape (the orchestrator keys scrapes by router id for exactly
  this). Hedge counters come from the LOAD router alone: it is the only
  process proxying client traffic, and the holder's idle hedge counters
  would just dilute the delta. `holders` is every lease holder_id observed
  since load start; two or more means actuation provably handed over."""
  statuses = statuses or {}
  baselines = baselines or {}
  holder_list = [h for h in (holders or ()) if h]

  def fleet_delta(key: str) -> int:
    total = 0
    for rid, status in statuses.items():
      cur = ((status or {}).get("fleet") or {}).get(key) or 0
      base = (((baselines.get(rid) or {}).get("fleet")) or {}).get(key) or 0
      total += max(0, int(cur) - int(base))
    return total

  def router_delta(key: str) -> int:
    total = 0
    for rid, status in statuses.items():
      cur = (status or {}).get(key) or 0
      base = (baselines.get(rid) or {}).get(key) or 0
      total += max(0, int(cur) - int(base))
    return total

  def hedge_delta(key: str) -> int:
    return max(0, int((load_router or {}).get(key) or 0)
               - int((load_baseline or {}).get(key) or 0))

  return {
    "routers": sorted(statuses),
    "holders_seen": holder_list,
    "holder_changed": len(holder_list) >= 2,
    "respawns": fleet_delta("respawns_total"),
    "respawn_failures": fleet_delta("respawn_failures_total"),
    "deaths": fleet_delta("deaths_total"),
    "scale_ups": fleet_delta("scale_ups_total"),
    "scale_downs": fleet_delta("scale_downs_total"),
    "retires": fleet_delta("retires_total"),
    "adopted": fleet_delta("adopted_total"),
    "spawn_failures": fleet_delta("spawn_failures_total"),
    # Soft warm-start evidence: prefixes the holder pre-announced into a
    # freshly (re)spawned replica. Reported, never gated — the hard warm
    # guarantee (compile-cache reuse) is engine-level unit territory.
    "warm_prefetch_announced": router_delta("prefetch_announced_total"),
    "hedges_fired": hedge_delta("hedges_fired_total"),
    "hedges_won": hedge_delta("hedges_won_total"),
    "hedge_cancelled": hedge_delta("hedge_cancelled_total"),
    "hedge_both_streamed": hedge_delta("hedge_both_streamed_total"),
    "expect": dict(expect or {}),
  }


def classify_aborts(abort_events: Iterable[dict],
                    fault_windows: Iterable[dict]) -> Dict[str, list]:
  """Split watchdog/deadline abort evidence into injected (inside an active
  fault window) vs false (no fault to blame). Each event: {node_id, ts,
  reason}; each window: {t0, t1} in the same clock (unix seconds)."""
  windows = [(float(w["t0"]), float(w["t1"])) for w in fault_windows]
  injected, false = [], []
  for ev in abort_events:
    ts = float(ev.get("ts") or 0.0)
    if any(t0 <= ts <= t1 for t0, t1 in windows):
      injected.append(dict(ev))
    else:
      false.append(dict(ev))
  return {"injected": injected, "false": false}


def leak_check(settle_a: Dict[str, dict], settle_b: Dict[str, dict],
               host_budget_bytes: Optional[float] = None) -> Dict[str, Any]:
  """Post-drain leak verdict from two settle scrapes (per-node flat
  /metrics samples, taken a few seconds apart once the load is gone).

  - `xot_active_requests` must be 0 on every reachable node in BOTH scrapes
    (a request the drain never finished is leaked engine/bookkeeping state);
  - `xot_kv_pool_pages_in_use` must not grow between the scrapes (prefix
    cache legitimately retains pages; growth with zero load is a leak);
  - `xot_kv_host_bytes` must respect the configured budget."""
  active = {}
  for node_id in set(settle_a) | set(settle_b):
    a = (settle_a.get(node_id) or {}).get("xot_active_requests", 0.0)
    b = (settle_b.get(node_id) or {}).get("xot_active_requests", 0.0)
    active[node_id] = max(float(a or 0.0), float(b or 0.0))
  pool_growth = {}
  host_over = {}
  for node_id, sb in settle_b.items():
    sa = settle_a.get(node_id) or {}
    pa, pb = sa.get("xot_kv_pool_pages_in_use"), sb.get("xot_kv_pool_pages_in_use")
    if pa is not None and pb is not None and float(pb) > float(pa):
      pool_growth[node_id] = float(pb) - float(pa)
    hb = sb.get("xot_kv_host_bytes")
    if hb is not None and host_budget_bytes and float(hb) > float(host_budget_bytes):
      host_over[node_id] = float(hb)
  leaked_active = {n: v for n, v in active.items() if v > 0}
  return {
    "active_requests": leaked_active,
    "pool_pages_growth": pool_growth,
    "host_bytes_over_budget": host_over,
    "ok": not leaked_active and not pool_growth and not host_over,
  }


def flatten_metrics(report: Dict[str, Any]) -> Dict[str, float]:
  """The flat, direction-suffixed metric names benchdiff diffs soak-to-soak
  (`*_s` = lower-better latency, `*_rps` = higher-better rate, counters
  spelled so drift reads correctly)."""
  out: Dict[str, float] = {}
  client = report.get("client", {})
  for key in ("ttft_s", "tpot_s", "e2e_s"):
    summary = client.get(key) or {}
    for p in ("p50", "p95", "p99"):
      v = summary.get(p)
      if v is not None:
        out[f"client_{key[:-2]}_{p}_s"] = round(float(v), 4)
  for k_src, k_out in (("submitted", "requests_submitted"), ("ok", "requests_ok"),
                       ("errors", "request_errors"), ("rejected", "requests_rejected"),
                       ("rps_achieved", "achieved_rps")):
    v = client.get(k_src)
    if v is not None:
      out[k_out] = float(v)
  server = report.get("server", {})
  for family in ("ttft_seconds", "request_seconds"):
    s = server.get(family) or {}
    for p in ("p50", "p95", "p99"):
      v = s.get(p)
      if v is not None:
        out[f"server_{family.replace('_seconds', '')}_{p}_s"] = round(float(v), 4)
  for counter in ("watchdog_aborts", "request_restarts", "peer_evictions",
                  "hop_retries", "dedup_drops", "admission_rejections"):
    v = server.get(counter)
    if v is not None:
      out[f"{counter}_total"] = float(v)
  overload = report.get("overload")
  if overload is not None:
    out["overload_watchdog_aborts"] = float(overload.get("watchdog_aborts_in_window", 0))
    out["overload_client_rejected"] = float(overload.get("client_rejected", 0))
  router = report.get("router")
  if router is not None:
    out["router_drains_total"] = float(router.get("drains_total", 0))
    out["router_readmits_total"] = float(router.get("readmits_total", 0))
    out["router_routed_while_out"] = float(
      sum((router.get("routed_while_out") or {}).values()))
    out["router_prefetch_announced"] = float(router.get("prefetch_announced_total", 0))
  fabric = report.get("fabric")
  if fabric is not None:
    out["kv_fabric_hits"] = float(fabric.get("hits") or 0)
    out["kv_fabric_misses"] = float(fabric.get("misses") or 0)
    out["kv_fabric_bytes"] = float(fabric.get("bytes") or 0)
    out["fabric_transfer_failures"] = float(fabric.get("errors") or 0)
    out["fabric_chained"] = float(fabric.get("router_chained") or 0)
    out["fabric_chain_failures"] = float(fabric.get("router_chain_failures") or 0)
  fleet = report.get("fleet")
  if fleet is not None:
    out["fleet_respawns"] = float(fleet.get("respawns") or 0)
    out["fleet_respawn_failures"] = float(fleet.get("respawn_failures") or 0)
    out["fleet_deaths"] = float(fleet.get("deaths") or 0)
    out["fleet_scale_ups"] = float(fleet.get("scale_ups") or 0)
    out["fleet_scale_downs"] = float(fleet.get("scale_downs") or 0)
    out["fleet_spawn_failures"] = float(fleet.get("spawn_failures") or 0)
    out["hedges_fired"] = float(fleet.get("hedges_fired") or 0)
    out["hedges_won"] = float(fleet.get("hedges_won") or 0)
    out["hedge_cancelled"] = float(fleet.get("hedge_cancelled") or 0)
    out["hedge_both_streamed"] = float(fleet.get("hedge_both_streamed") or 0)
  aborts = report.get("aborts") or {}
  out["false_aborts"] = float(len(aborts.get("false") or ()))
  leaks = report.get("leaks") or {}
  out["leaked_requests"] = float(sum((leaks.get("active_requests") or {}).values()))
  out["pool_page_leaks"] = float(sum((leaks.get("pool_pages_growth") or {}).values()))
  alerts = report.get("alerts")
  if alerts is not None:
    out["alert_firings_total"] = float(len(alerts.get("firings") or ()))
    out["alert_firings_outside_fault_windows"] = float(
      alerts.get("outside_fault_windows", 0))
    out["alerts_fired_and_resolved"] = float(
      alerts.get("fired_and_resolved_in_window", 0))
  drift = report.get("drift")
  if drift is not None:
    out["drift_firings_total"] = float(len(drift.get("firings") or ()))
    out["drift_firings_outside_fault_windows"] = float(
      drift.get("outside_fault_windows", 0))
    if "router_named_total" in drift:
      out["router_drift_named"] = float(drift.get("router_named_total") or 0)
  history = report.get("history")
  if history is not None:
    out["history_samples_total"] = float(history.get("samples_total") or 0)
    out["history_restarts_total"] = float(history.get("restarts_total") or 0)
  anatomy = report.get("anatomy")
  if anatomy is not None:
    out["anatomy_breakdowns"] = float(anatomy.get("breakdowns") or 0)
    out["anatomy_unattributed_share"] = float(
      anatomy.get("unattributed_share_mean") or 0.0)
  return out


def evaluate(report: Dict[str, Any]) -> Dict[str, Any]:
  """Stamp the verdict: `green` iff reconciliation holds, no false aborts,
  no leaks, no alert firing outside a fault window, and no client errors
  landed OUTSIDE a fault window. Returns the report with `verdict`,
  `reasons`, and flat `metrics` filled in."""
  reasons: List[str] = []
  for name, row in (report.get("reconciliation") or {}).items():
    if row.get("ok") is False:
      reasons.append(
        f"reconciliation: {name} client={row.get('client_s')}s "
        f"server={row.get('server_s')}s disagree beyond tolerance")
  false_aborts = (report.get("aborts") or {}).get("false") or []
  for ev in false_aborts:
    reasons.append(f"false abort: {ev.get('node_id')} at ts={ev.get('ts')}: "
                   f"{str(ev.get('reason'))[:120]}")
  unattributed = (report.get("aborts") or {}).get("unattributed", 0)
  if unattributed:
    reasons.append(f"{unattributed} watchdog abort(s) with no flight snapshot to classify")
  leaks = report.get("leaks") or {}
  if leaks and not leaks.get("ok", True):
    reasons.append(f"leaks: {json.dumps({k: v for k, v in leaks.items() if k != 'ok'})}")
  for fired in ((report.get("alerts") or {}).get("firings") or ()):
    if not fired.get("in_fault_window"):
      reasons.append(
        f"alert fired outside any fault window: {fired.get('rule')} on "
        f"{fired.get('node_id')} at ts={fired.get('fired_at')}"
        + (f" (suspect {fired.get('suspect')})" if fired.get("suspect") else ""))
  for fired in ((report.get("drift") or {}).get("firings") or ()):
    # Same zero-tolerance as the SLO rows: a chronic sentinel that names
    # rot on healthy traffic is paging noise, not a detector.
    if not fired.get("in_fault_window"):
      reasons.append(
        f"perf_drift fired outside any fault window: {fired.get('rule')} on "
        f"{fired.get('node_id')} at ts={fired.get('fired_at')}")
  client = report.get("client") or {}
  outside = client.get("errors_outside_fault_windows", 0)
  if outside:
    reasons.append(f"{outside} client error(s) outside any fault window")
  if not client.get("submitted"):
    reasons.append("no requests were submitted")
  overload = report.get("overload")
  if overload is not None:
    # Overload must be SURVIVED, not shed as aborts: the PR 8 failure mode
    # (watchdog "stalled" aborts under above-capacity load) is a red in its
    # own right, and an overload phase that recorded no rejection at all
    # never actually exercised the gate.
    aborts_in = overload.get("watchdog_aborts_in_window", 0)
    if aborts_in:
      reasons.append(
        f"overload: {aborts_in} watchdog abort(s) inside the overload window "
        "— load was shed as aborts, not 429s")
    if overload.get("server_admission_rejections", 0) < 1:
      reasons.append("overload: no admission rejection recorded — the phase "
                     "never drove the gate past its bound")
  router = report.get("router")
  if router is not None:
    for name, n in sorted((router.get("routed_while_out") or {}).items()):
      if n > 0:
        reasons.append(f"router: {n} request(s) routed to {name} while it was "
                       "out of rotation (draining/probing)")
    if router.get("expect_drain"):
      if router.get("drains_total", 0) < 1:
        reasons.append("router: injected gray failure drove no replica to draining")
      if router.get("readmits_total", 0) < 1:
        reasons.append("router: no drained replica was readmitted after the fault cleared")
  fleet = report.get("fleet")
  if fleet is not None:
    # The elastic-fleet green bar. Failure counters are zero-tolerance
    # (a respawn or spawn that did not come up healthy is the exact outage
    # the controller exists to prevent; both hedge legs streaming is a
    # double-billed request). Each positive expectation is asserted only
    # when the run staged its fault — and client errors red at ANY count,
    # in-window or not: the fleet's whole contract is that every injected
    # fault stays invisible to clients.
    if float(fleet.get("respawn_failures") or 0) > 0:
      reasons.append(f"fleet: {fleet.get('respawn_failures')} respawn(s) never "
                     "came back healthy inside the boot timeout")
    if float(fleet.get("spawn_failures") or 0) > 0:
      reasons.append(f"fleet: {fleet.get('spawn_failures')} spawn attempt(s) "
                     "failed outright (template argv/env is broken)")
    if float(fleet.get("hedge_both_streamed") or 0) > 0:
      reasons.append(f"fleet: {fleet.get('hedge_both_streamed')} hedged "
                     "request(s) streamed from BOTH legs (loser not cancelled)")
    exp = fleet.get("expect") or {}
    if exp.get("respawn") and float(fleet.get("respawns") or 0) < 1:
      reasons.append("fleet: a replica was SIGKILLed but no controller "
                     "respawn landed")
    if exp.get("scale_up") and float(fleet.get("scale_ups") or 0) < 1:
      reasons.append("fleet: the surge never drove a scale-up into a "
                     "latent slot")
    if exp.get("hedge_win") and float(fleet.get("hedges_won") or 0) < 1:
      reasons.append("fleet: the injected stall produced no won hedge "
                     "(no alternate leg beat the slow primary)")
    if exp.get("holder_change") and not fleet.get("holder_changed"):
      reasons.append("fleet: the lease holder was killed but no surviving "
                     f"router took over (holders seen: {fleet.get('holders_seen')})")
    if client.get("errors"):
      reasons.append(f"fleet: {client.get('errors')} client error(s) — the "
                     "elastic-fleet bar is zero errors TOTAL, fault windows "
                     "included")
  fabric = report.get("fabric")
  if fabric is not None:
    # The fabric green bar: zero dropped transfers (a torn/stale blob must
    # degrade to cold prefill in unit tests; two healthy processes on
    # localhost have no excuse to tear one), and — when the run expects a
    # hit — the router actually chained through the prefill replica and at
    # least one REAL cross-replica import landed in the load window. Chain
    # FAILURES are informational (the documented degradation is a plain
    # cold forward, not an error).
    if float(fabric.get("errors") or 0) > 0:
      reasons.append(f"fabric: {float(fabric.get('errors') or 0):g} transfer(s) "
                     "dropped (peer error, torn blob, or digest mismatch)")
    if fabric.get("expect_hit"):
      if float(fabric.get("router_chained") or 0) < 1:
        reasons.append("fabric: router chained no request through the prefill replica")
      if float(fabric.get("hits") or 0) < 1:
        reasons.append("fabric: no cross-replica KV import landed during the load window")
  report["reasons"] = reasons
  report["verdict"] = "green" if not reasons else "red"
  report["metrics"] = flatten_metrics(report)
  return report


def write_report(report: Dict[str, Any], path) -> Path:
  path = Path(path)
  path.write_text(json.dumps(report, indent=1, sort_keys=False) + "\n")
  return path
