"""Open-loop load generator for the soak harness.

Open-loop means arrivals are scheduled from the arrival PROCESS, never from
completions: a slow server faces the same offered load a fast one does, so
queueing delay shows up in the measurements instead of silently throttling
the experiment (the classic closed-loop coordinated-omission trap).

Pieces:
- `arrival_offsets`: Poisson (exponential inter-arrival) or bursty
  (Poisson bursts of B back-to-back arrivals) schedules, precomputed and
  deterministic under a seed;
- `PromptFactory`: prompt-length distribution (word count lognormal-ish via
  choice buckets) and a session pool — with probability `reuse_p` a request
  re-sends a session's long shared prefix plus a fresh tail, exercising the
  prefix cache exactly like a returning chat user;
- `run_load`: fires one HTTP task per arrival against the ring's OpenAI
  API (mixed streaming/non-streaming per `stream_fraction`), capturing
  per-request client-side TTFT (first content chunk), TPOT (mean
  inter-chunk gap), and e2e wall time.
"""
from __future__ import annotations

import asyncio
import json
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_WORDS = (
  "ring", "shard", "layer", "token", "page", "prefix", "decode", "prefill",
  "tensor", "batch", "cache", "stream", "sample", "weight", "device", "host",
)


def arrival_offsets(kind: str, rate_rps: float, seconds: float, rng: random.Random,
                    burst_size: int = 4, burst_every_s: Optional[float] = None) -> List[float]:
  """Arrival times (seconds from load start, ascending) for the whole run.

  poisson: exponential inter-arrivals at `rate_rps`.
  bursty:  bursts of `burst_size` back-to-back arrivals, burst STARTS
           Poisson at rate_rps/burst_size (same mean offered load), or on a
           fixed cadence when `burst_every_s` is given."""
  if rate_rps <= 0 or seconds <= 0:
    return []
  out: List[float] = []
  t = 0.0
  if kind == "poisson":
    while True:
      t += rng.expovariate(rate_rps)
      if t >= seconds:
        return out
      out.append(t)
  if kind == "bursty":
    burst_rate = rate_rps / max(1, burst_size)
    while True:
      t += (burst_every_s if burst_every_s else rng.expovariate(burst_rate))
      if t >= seconds:
        return out
      out.extend([t] * burst_size)
  raise ValueError(f"unknown arrival kind {kind!r} (poisson|bursty)")


class PromptFactory:
  """Deterministic prompts with a session/prefix-reuse mix.

  `length_buckets` is a (word_count, weight) distribution; a session's
  prefix is a fixed ~3/4-bucket head re-sent verbatim on reuse, so the
  serving side sees the page-granular warm path a returning user drives."""

  def __init__(self, rng: random.Random, length_buckets=((8, 4), (24, 3), (64, 2), (160, 1)),
               sessions: int = 8, reuse_p: float = 0.3):
    self.rng = rng
    self.lengths = [w for w, _ in length_buckets]
    self.weights = [wt for _, wt in length_buckets]
    self.reuse_p = reuse_p
    self._session_prefixes = [self._words(96, tag=f"session-{i}") for i in range(max(0, sessions))]

  def _words(self, n: int, tag: str = "") -> str:
    toks = [tag] if tag else []
    toks += [self.rng.choice(_WORDS) for _ in range(n)]
    return " ".join(toks)

  def next_prompt(self, i: int) -> Dict[str, object]:
    n = self.rng.choices(self.lengths, weights=self.weights)[0]
    if self._session_prefixes and self.rng.random() < self.reuse_p:
      sid = self.rng.randrange(len(self._session_prefixes))
      text = f"{self._session_prefixes[sid]} {self._words(max(4, n // 4), tag=f'turn-{i}')}"
      return {"prompt": text, "session": sid, "words": n}
    return {"prompt": self._words(n, tag=f"req-{i}"), "session": None, "words": n}


@dataclass
class ClientRecord:
  index: int
  offset_s: float
  streamed: bool
  session: Optional[int]
  t_submit: float = 0.0  # unix seconds
  status: Optional[int] = None
  ok: bool = False
  # 429 at the admission gate (or relayed by the router): load the server
  # SHED on purpose, counted separately from errors — "rejected, not
  # aborted" is precisely the overload verdict the soak proves.
  rejected: bool = False
  error: Optional[str] = None
  ttft_s: Optional[float] = None
  tpot_s: Optional[float] = None
  e2e_s: Optional[float] = None
  content_len: int = 0
  chunks: int = 0
  # Raw inter-chunk gaps (seconds): the per-token-shaped client sample the
  # TPOT reconciliation compares against the server's `xot_token_seconds`
  # histogram — a per-request MEAN (tpot_s) is a different distribution.
  tpot_gaps: List[float] = field(default_factory=list)


@dataclass
class LoadPlan:
  seconds: float
  rate_rps: float
  arrival: str = "poisson"
  stream_fraction: float = 0.5
  session_reuse: float = 0.3
  max_tokens: int = 16
  model: str = "synthetic-tiny"
  seed: int = 1234
  burst_size: int = 4
  request_timeout_s: float = 120.0
  # Extra open-loop arrival windows LAYERED on the base schedule — the
  # overload phase's shapes: {"at_s", "seconds", "rate_rps"} (a Poisson
  # window) or {"at_s", "count"} (`count` SIMULTANEOUS arrivals — the
  # deterministic above-capacity burst: a rate window can be absorbed by a
  # fast machine, a same-instant batch larger than every admission queue
  # cannot). Offered load is base + extra, never completion-throttled.
  extra_phases: List[dict] = field(default_factory=list)
  records: List[ClientRecord] = field(default_factory=list)


async def _do_request(session, port: int, plan: LoadPlan, rec: ClientRecord,
                      prompt: str) -> None:
  body = {
    "model": plan.model,
    "messages": [{"role": "user", "content": prompt}],
    "max_tokens": plan.max_tokens, "temperature": 0,
  }
  if rec.streamed:
    body["stream"] = True
  url = f"http://127.0.0.1:{port}/v1/chat/completions"
  t0 = time.monotonic()
  rec.t_submit = time.time()
  try:
    async with session.post(url, json=body) as resp:
      rec.status = resp.status
      if resp.status == 429:
        # Admission-control shed: a deliberate, well-formed rejection (the
        # body carries queue depth + Retry-After), not a failure.
        rec.rejected = True
        rec.e2e_s = time.monotonic() - t0
        await resp.read()
        return
      if not rec.streamed:
        data = await resp.json()
        rec.e2e_s = time.monotonic() - t0
        if resp.status == 200:
          content = (data.get("choices") or [{}])[0].get("message", {}).get("content", "")
          rec.content_len = len(content or "")
          rec.ok = bool(content)
          if not rec.ok:
            rec.error = "empty completion"
        else:
          rec.error = json.dumps(data)[:200]
        return
      # SSE: one line per event; first non-empty delta content = TTFT.
      chunk_times: List[float] = []
      done = False
      async for raw in resp.content:
        line = raw.decode("utf-8", "replace").strip()
        if not line.startswith("data: "):
          continue
        payload = line[len("data: "):]
        if payload == "[DONE]":
          done = True
          break
        try:
          event = json.loads(payload)
        except json.JSONDecodeError:
          continue
        if "error" in event:
          rec.error = json.dumps(event["error"])[:200]
          break
        delta = (event.get("choices") or [{}])[0].get("delta", {})
        content = delta.get("content") or ""
        if content:
          now = time.monotonic()
          if rec.ttft_s is None:
            rec.ttft_s = now - t0
          chunk_times.append(now)
          rec.content_len += len(content)
          rec.chunks += 1
      rec.e2e_s = time.monotonic() - t0
      if len(chunk_times) >= 2:
        rec.tpot_s = (chunk_times[-1] - chunk_times[0]) / (len(chunk_times) - 1)
        rec.tpot_gaps = [b - a for a, b in zip(chunk_times, chunk_times[1:])]
      rec.ok = done and rec.error is None and rec.status == 200 and rec.content_len > 0
      if not rec.ok and rec.error is None:
        rec.error = f"stream ended early (done={done}, content={rec.content_len})"
  except Exception as e:
    rec.e2e_s = time.monotonic() - t0
    rec.error = f"{type(e).__name__}: {e}"[:200]


async def run_load(port: int, plan: LoadPlan) -> List[ClientRecord]:
  """Fire the whole open-loop schedule; returns per-request records (also
  left on plan.records). Arrivals that the event loop delivers late still
  count from their ACTUAL send time — client latencies never include
  scheduler lag."""
  import aiohttp
  rng = random.Random(plan.seed)
  offsets = arrival_offsets(plan.arrival, plan.rate_rps, plan.seconds, rng,
                            burst_size=plan.burst_size)
  for phase in plan.extra_phases:
    if phase.get("count"):
      extra = [0.0] * int(phase["count"])
    else:
      extra = arrival_offsets("poisson", float(phase["rate_rps"]),
                              float(phase["seconds"]), rng)
    offsets = sorted(offsets + [float(phase["at_s"]) + o for o in extra])
  prompts = PromptFactory(rng, reuse_p=plan.session_reuse)
  plan.records = []
  tasks: List[asyncio.Task] = []
  timeout = aiohttp.ClientTimeout(total=plan.request_timeout_s)
  connector = aiohttp.TCPConnector(limit=256)
  t_start = time.monotonic()
  async with aiohttp.ClientSession(timeout=timeout, connector=connector) as session:
    for i, off in enumerate(offsets):
      delay = t_start + off - time.monotonic()
      if delay > 0:
        await asyncio.sleep(delay)
      spec = prompts.next_prompt(i)
      rec = ClientRecord(index=i, offset_s=off,
                         streamed=rng.random() < plan.stream_fraction,
                         session=spec["session"])
      plan.records.append(rec)
      tasks.append(asyncio.ensure_future(
        _do_request(session, port, plan, rec, spec["prompt"])))
    if tasks:
      await asyncio.gather(*tasks, return_exceptions=True)
  return plan.records
