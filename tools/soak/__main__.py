"""CLI: `python -m tools.soak` — multi-process SLO soak with a verdict.

  python -m tools.soak --smoke
      CI shape: 2-process CPU ring, ~60 s of Poisson load (mixed streaming,
      session reuse), ONE injected kill mid-run, green `SOAK_*.json`
      verdict required (exit 0 = green, 1 = red).

  python -m tools.soak --seconds 600 --rps 4 --procs 3 --arrival bursty \
      --kill 1@120 --rules '1@300+30:[{"rpc":"SendTensor","action":"delay","nth":1,"times":1000,"delay_s":0.2}]'
      Long-form soak: any ring size, arrival process, and wall-clock fault
      schedule (kill = SIGKILL the node process; rules = install injector
      rules in a child over /v1/debug/faults for a timed phase).

Defaults come from the XOT_SOAK_* knobs (utils/knobs.py) so CI can retune
without editing workflows. The verdict report is written to --out (default
SOAK_<tag>.json) and is diffable/gateable with `python -m tools.benchdiff`.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
if str(REPO) not in sys.path:
  sys.path.insert(0, str(REPO))

from xotorch_tpu.utils import knobs


def _parse_kill(spec: str):
  """node_index@at_s[+grace_s], e.g. `1@25` or `1@25+45`."""
  from tools.soak.orchestrator import FaultPhase
  node, _, when = spec.partition("@")
  at, _, grace = when.partition("+")
  return FaultPhase(kind="kill", node=int(node), at_s=float(at),
                    grace_s=float(grace) if grace else 45.0)


def _parse_rules(spec: str):
  """node_index@at_s+hold_s:<json rules>, e.g.
  `1@30+20:[{"rpc":"SendTensor","action":"delay","nth":1,"times":999,"delay_s":0.1}]`."""
  from tools.soak.orchestrator import FaultPhase
  head, _, rules_json = spec.partition(":")
  node, _, when = head.partition("@")
  at, _, hold = when.partition("+")
  at_f = float(at)
  hold_f = float(hold) if hold else 15.0
  return FaultPhase(kind="rules", node=int(node), at_s=at_f, until_s=at_f + hold_f,
                    rules=json.loads(rules_json))


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(
    prog="python -m tools.soak",
    description="Open-loop load + multi-process ring soak with a green/red "
                "SLO verdict (reconciliation, false aborts, leaks).")
  parser.add_argument("--smoke", action="store_true",
                      help="CI smoke shape: 2 procs, ~60 s Poisson, one injected kill")
  parser.add_argument("--router-smoke", action="store_true",
                      help="front-door smoke: router + 2 single-node replicas, an "
                           "above-capacity overload burst (shed as 429s, never "
                           "watchdog aborts) and an injected gray failure one "
                           "replica is drained for and readmitted after")
  parser.add_argument("--fabric-smoke", action="store_true",
                      help="disaggregated-serving smoke: router + a prefill "
                           "replica (out of rotation) + a decode replica; every "
                           "fresh prompt chains prefill -> KV offer -> decode "
                           "and the verdict requires >= 1 real cross-replica "
                           "KV import with zero dropped transfers")
  parser.add_argument("--fleet-smoke", action="store_true",
                      help="elastic-fleet smoke: TWO routers (lease-holder + "
                           "load router) over a fleet template with a latent "
                           "spare; SIGKILL a replica (controller respawn, warm "
                           "via the shared compile cache), SIGKILL the holder "
                           "router (survivor takes the lease), a surge burst "
                           "(scale-up into the spare), and an injected stall "
                           "(hedge fires and wins) — green requires all four "
                           "AND zero client errors total")
  parser.add_argument("--seconds", type=float, default=None)
  parser.add_argument("--rps", type=float, default=None)
  parser.add_argument("--procs", type=int, default=None)
  parser.add_argument("--arrival", choices=("poisson", "bursty"), default="poisson")
  parser.add_argument("--stream-fraction", type=float, default=None)
  parser.add_argument("--session-reuse", type=float, default=None)
  parser.add_argument("--max-tokens", type=int, default=None,
                      help="completion length per request (default 16; 8 under --smoke)")
  parser.add_argument("--model", default="synthetic-tiny")
  parser.add_argument("--seed", type=int, default=None)
  parser.add_argument("--kill", action="append", default=[],
                      help="inject a SIGKILL: node_index@at_s[+grace_s] (repeatable)")
  parser.add_argument("--rules", action="append", default=[],
                      help="timed injector phase: node@at_s+hold_s:<json rules> (repeatable)")
  parser.add_argument("--recon-tol-s", type=float, default=None,
                      help="client-vs-server percentile slack (default XOT_SOAK_RECON_TOL_S)")
  parser.add_argument("--tag", default=None, help="report tag (SOAK_<tag>.json)")
  parser.add_argument("--out", default=None, help="report path (default SOAK_<tag>.json)")
  parser.add_argument("--log-dir", default=None, help="keep child logs here (default: temp dir)")
  parser.add_argument("--json", action="store_true", help="print the full report JSON")
  args = parser.parse_args(argv)

  from tools.soak.orchestrator import SoakConfig, run_soak
  cfg = SoakConfig(
    procs=args.procs if args.procs is not None else knobs.get_int("XOT_SOAK_PROCS"),
    seconds=args.seconds if args.seconds is not None else knobs.get_float("XOT_SOAK_SECONDS"),
    rate_rps=args.rps if args.rps is not None else knobs.get_float("XOT_SOAK_RPS"),
    arrival=args.arrival,
    stream_fraction=(args.stream_fraction if args.stream_fraction is not None
                     else knobs.get_float("XOT_SOAK_STREAM_FRACTION")),
    session_reuse=(args.session_reuse if args.session_reuse is not None
                   else knobs.get_float("XOT_SOAK_SESSION_REUSE")),
    max_tokens=args.max_tokens if args.max_tokens is not None else 16,
    model=args.model,
    seed=args.seed if args.seed is not None else knobs.get_int("XOT_SOAK_SEED"),
    recon_tol_s=(args.recon_tol_s if args.recon_tol_s is not None
                 else knobs.get_float("XOT_SOAK_RECON_TOL_S")),
    log_dir=args.log_dir,
  )
  cfg.tag = args.tag or ("smoke" if args.smoke
                         else "router" if args.router_smoke
                         else "fabric" if args.fabric_smoke
                         else "fleet" if args.fleet_smoke else "run")
  if sum((args.smoke, args.router_smoke, args.fabric_smoke, args.fleet_smoke)) > 1:
    print("soak: --smoke, --router-smoke, --fabric-smoke and --fleet-smoke "
          "are mutually exclusive", file=sys.stderr)
    return 2
  if args.router_smoke:
    # The front-door acceptance shape: two independent single-node replicas
    # behind the router, admission gates ON (ROUTER_REPLICA_ENV), base load
    # comfortably subcritical. Phase 1 (overload): an above-capacity burst
    # that must be shed as 429s with zero watchdog aborts. Phase 2 (gray
    # failure): a ProcessPrompt delay on replica 1 — 10 s against a 6 s SLO
    # target, health checks green — that must fire its burn-rate alert,
    # drain the replica (inflight completes, new traffic fails over), and
    # end in readmission once the fault clears. recon_tol_s is wide because
    # queue waits and the injected delay are client-visible by design; the
    # structural server-never-over-client bound stays tight.
    cfg.router = True
    cfg.replicas = 2
    if args.seconds is None:
      cfg.seconds = 110.0
    if args.rps is None:
      cfg.rate_rps = 0.4
    if args.max_tokens is None:
      cfg.max_tokens = 6
    if args.recon_tol_s is None:
      cfg.recon_tol_s = 30.0
    # A SIMULTANEOUS 24-request burst: with max_inflight=1 + queue_depth=2
    # per replica, at most 6 can be admitted/queued across the fleet at one
    # instant — the rest MUST be 429s no matter how fast the machine is (a
    # rate-shaped burst gets absorbed by a fast CI runner).
    cfg.overload = {"at_s": 8.0, "count": 24}
    cfg.gray = {"node": 1, "at_s": 24.0, "hold_s": 24.0, "delay_s": 10.0}
  if args.fabric_smoke:
    # The disaggregated-serving acceptance shape: replica 0 boots as a
    # PREFILL replica (excluded from rotation, answers with kv.handles),
    # replica 1 decodes, and the router awaits the prefill -> offer chain
    # before every forward. No injected faults: the green bar here is the
    # fabric itself — at least one real cross-replica KV import, zero
    # dropped transfers — on top of the usual reconciliation / false-abort
    # / leak rules. recon_tol_s is wide because the awaited chain (prefill
    # compute + offer hop) is client-visible wall time the decode server's
    # histograms structurally never see.
    cfg.router = True
    cfg.fabric = True
    cfg.replicas = 2
    if args.seconds is None:
      cfg.seconds = 90.0
    if args.rps is None:
      cfg.rate_rps = 0.3
    if args.max_tokens is None:
      cfg.max_tokens = 6
    if args.recon_tol_s is None:
      cfg.recon_tol_s = 30.0
  if args.fleet_smoke:
    # The elastic-fleet acceptance arc, on one 140 s clock:
    #   t=18  SIGKILL rep1        -> the lease holder declares it dead after
    #                                3 unclean polls and respawns it from the
    #                                template (warm: same compile cache, and
    #                                the holder pre-announces hot prefixes)
    #   t=55  SIGKILL routerA     -> the lease expires (5 s TTL) and routerB
    #                                takes over actuation without dropping a
    #                                single proxied request
    #   t=75  24-request burst    -> per-replica admission queues mark their
    #                                high-water, three pressured ticks later
    #                                the controller scales into latent rep2
    #   t=100 4 s ProcessPrompt   -> slower than the 1.5 s hedge floor but
    #         stall on rep0          inside the 6 s SLO: the hedge fires, the
    #                                other replica wins, the loser is aborted
    # Streaming is OFF by design: the zero-client-errors bar is structural
    # only for non-streamed requests (a connect-refused or broken-mid-read
    # body transparently retries on another replica; a stream past its
    # first byte cannot). recon_tol_s is wide because queue waits, failover
    # retries and hedge delays are client-visible wall time by design.
    cfg.router = True
    cfg.fleet = True
    cfg.replicas = 2
    if args.seconds is None:
      cfg.seconds = 140.0
    if args.rps is None:
      cfg.rate_rps = 0.35
    if args.max_tokens is None:
      cfg.max_tokens = 6
    if args.stream_fraction is None:
      cfg.stream_fraction = 0.0
    if args.recon_tol_s is None:
      cfg.recon_tol_s = 30.0
    cfg.overload = {"at_s": 75.0, "count": 24}
    cfg.fleet_kill_router_at_s = 55.0
    cfg.faults.append(_parse_kill("1@18+60"))
    from tools.soak.orchestrator import FaultPhase
    cfg.faults.append(FaultPhase(
      kind="rules", node=0, at_s=100.0, until_s=128.0, grace_s=45.0,
      rules=[{"rpc": "ProcessPrompt", "action": "delay", "nth": 1,
              "times": 1000000, "delay_s": 4.0}]))
  if args.smoke:
    # The acceptance shape: one mid-run kill of the non-API node, load
    # sized so a laptop/CI runner finishes the whole arc in a few minutes.
    # The rate MUST stay subcritical for a CPU ring (~12 tok/s aggregate
    # service): an open-loop rate above capacity grows the queue without
    # bound until the stall watchdog starts shedding load as "stalled"
    # aborts — a real overload behavior, but not the false-abort question
    # this smoke exists to answer. Explicit --rps/--max-tokens still win.
    cfg.procs = max(2, cfg.procs)
    if args.rps is None:
      cfg.rate_rps = 0.25
    if args.max_tokens is None:
      cfg.max_tokens = 8
    kill_at = max(10.0, cfg.seconds * 0.4)
    cfg.faults.append(_parse_kill(f"{cfg.procs - 1}@{kill_at:g}"))
  cfg.faults.extend(_parse_kill(s) for s in args.kill)
  cfg.faults.extend(_parse_rules(s) for s in args.rules)
  node_count = cfg.replicas if cfg.router else cfg.procs
  for phase in cfg.faults:
    if phase.kind == "kill_router":
      continue  # targets the holder router, not a ring node
    if not 0 <= phase.node < node_count:
      print(f"soak: fault names node {phase.node} but the run has {node_count} node(s)",
            file=sys.stderr)
      return 2
  cfg.out = args.out or f"SOAK_{cfg.tag}.json"

  try:
    report = asyncio.run(run_soak(cfg))
  except Exception as e:
    # A dead ring or failed warmup is a soak verdict, not a traceback.
    print(f"soak: run failed: {e!r}", file=sys.stderr)
    return 2
  if args.json:
    print(json.dumps(report, indent=1))
  client = report.get("client", {})
  print(f"soak[{cfg.tag}]: verdict={report['verdict']} "
        f"requests={client.get('ok')}/{client.get('submitted')} ok "
        f"(errors in/out of fault windows: {client.get('errors_in_fault_windows')}/"
        f"{client.get('errors_outside_fault_windows')})")
  for name, row in sorted((report.get("reconciliation") or {}).items()):
    print(f"  recon {name}: client={row.get('client_s')} server={row.get('server_s')} "
          f"ok={row.get('ok')}")
  ab = report.get("aborts") or {}
  print(f"  aborts: injected={len(ab.get('injected') or ())} "
        f"false={len(ab.get('false') or ())} unattributed={ab.get('unattributed', 0)}; "
        f"leaks ok={report.get('leaks', {}).get('ok')}; report={cfg.out}")
  al = report.get("alerts") or {}
  print(f"  alerts: firings={len(al.get('firings') or ())} "
        f"outside_fault_windows={al.get('outside_fault_windows', 0)} "
        f"fired_and_resolved={al.get('fired_and_resolved_in_window', 0)}")
  dr = report.get("drift") or {}
  hi = report.get("history") or {}
  print(f"  drift: firings={len(dr.get('firings') or ())} "
        f"outside_fault_windows={dr.get('outside_fault_windows', 0)} "
        f"router_named={dr.get('router_named_total', 0)}; "
        f"history: samples={hi.get('samples_total', 0)} "
        f"restarts={hi.get('restarts_total', 0)}")
  ov = report.get("overload")
  if ov is not None:
    print(f"  overload: client_rejected={ov.get('client_rejected')} "
          f"server_rejections={ov.get('server_admission_rejections')} "
          f"aborts_in_window={ov.get('watchdog_aborts_in_window')}")
  rt = report.get("router")
  if rt is not None:
    print(f"  router: drains={rt.get('drains_total')} readmits={rt.get('readmits_total')} "
          f"routed_while_out={sum((rt.get('routed_while_out') or {}).values())} "
          f"prefetch_announced={rt.get('prefetch_announced_total')}")
  fb = report.get("fabric")
  if fb is not None:
    print(f"  fabric: hits={fb.get('hits')} misses={fb.get('misses')} "
          f"errors={fb.get('errors')} bytes={fb.get('bytes')} "
          f"chained={fb.get('router_chained')} "
          f"chain_failures={fb.get('router_chain_failures')}")
  fl = report.get("fleet")
  if fl is not None:
    print(f"  fleet: respawns={fl.get('respawns')} "
          f"respawn_failures={fl.get('respawn_failures')} "
          f"deaths={fl.get('deaths')} scale_ups={fl.get('scale_ups')} "
          f"holders={','.join(fl.get('holders_seen') or ()) or '-'} "
          f"warm_prefixes={fl.get('warm_prefetch_announced')}")
    print(f"  hedge: won/fired={fl.get('hedges_won')}/{fl.get('hedges_fired')} "
          f"cancelled={fl.get('hedge_cancelled')} "
          f"both_streamed={fl.get('hedge_both_streamed')}")
  for reason in report.get("reasons", []):
    print(f"  RED: {reason}")
  rc = 0 if report.get("verdict") == "green" else 1
  if rc == 0 and not cfg.fleet and any(p.kind == "kill" for p in cfg.faults):
    # A kill phase must PROVE the alert machine end to end: at least one
    # alert fired inside the kill window and resolved after the fault
    # cleared. A green run with a silent alert engine is not green. Fleet
    # runs are exempt BY DESIGN: there the killed process is a whole
    # single-node ring whose alert engine dies with it, the survivors see
    # only failed-over traffic, and the end-to-end proof is the fleet
    # section's own bar (respawn landed, zero client errors).
    if al.get("fired_and_resolved_in_window", 0) < 1:
      print("  RED: kill phase produced no fired-then-resolved alert "
            "(the burn-rate rules slept through an injected fault)")
      rc = 1
  return rc


if __name__ == "__main__":
  sys.exit(main())
