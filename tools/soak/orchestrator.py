"""Soak orchestrator: a real N-process ring + load + faults + scrapes.

Spawns `xotorch_tpu.main` node processes over localhost gRPC/UDP exactly
like the cross-process test suite (tests/xproc_harness owns the child
environment contract), drives tools/soak/loadgen against node 0's OpenAI
API, executes a wall-clock fault schedule (SIGKILL a node process, or
install drop/delay injector rules in a child via its /v1/debug/faults
endpoint), and continuously scrapes every node's /metrics and
/v1/debug/flight plus node 0's /v1/cluster/metrics and /v1/perf. The
verdict math lives in tools/soak/__init__ — this module only collects.
"""
from __future__ import annotations

import asyncio
import json
import os
import re
import signal
import sys
import time
import urllib.request
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

REPO = Path(__file__).resolve().parent.parent.parent
if str(REPO) not in sys.path:
  sys.path.insert(0, str(REPO))

from tools import soak as verdicts
from tools.soak.loadgen import LoadPlan, run_load
from xotorch_tpu.utils import knobs

_PROM_LINE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{[^}]*\})?\s+([0-9eE+.\-]+|NaN|Inf)\s*$")


def parse_prom(text: str) -> Dict[str, float]:
  """Flat {metric_name: value} view of a /metrics exposition (labels
  dropped, same-name series summed — one node per process here)."""
  out: Dict[str, float] = {}
  for line in text.splitlines():
    if line.startswith("#"):
      continue
    m = _PROM_LINE.match(line.strip())
    if not m:
      continue
    try:
      v = float(m.group(2))
    except ValueError:
      continue
    out[m.group(1)] = out.get(m.group(1), 0.0) + v
  return out


# Alert knobs for soak children: CI-timescale windows so a smoke's single
# mid-run kill provably drives pending -> firing -> resolved INSIDE the run
# (the production defaults' 2/10-minute windows and 14.4x/6x thresholds are
# sized for real traffic and would outlive the whole smoke). The error
# budget is loose enough that only an actual failure burst burns it, and
# the latency targets stay at their (CPU-safe) defaults.
SOAK_ALERT_ENV = {
  "XOT_ALERT_EVAL_S": "1",
  "XOT_ALERT_FAST_S": "15",
  "XOT_ALERT_SLOW_S": "45",
  "XOT_ALERT_BURN_FAST": "2",
  "XOT_ALERT_BURN_SLOW": "1",
  "XOT_ALERT_PENDING_S": "1",
  "XOT_ALERT_RESOLVE_S": "5",
  "XOT_SLO_ERROR_RATE": "0.05",
  # With burn thresholds this low, keep the latency budget WIDE (10% of
  # requests may miss the CPU-safe latency targets) so a loaded CI runner
  # can't fire a latency rule outside the fault window — the kill detector
  # here is the error-rate rule.
  "XOT_SLO_TARGET": "0.9",
  # CI-timescale history: 2 s samples so a one-minute smoke still records
  # a meaningful downsampled series for the report's history section. The
  # node-side drift sentinel is effectively OFF (pending hold longer than
  # any smoke): its peer-median arm needs only XOT_DRIFT_MIN_SAMPLES in
  # the current window — no chronic baseline — so a loaded CI runner's
  # hop-RTT jitter between ring nodes could otherwise fire perf_drift
  # outside any fault window, a zero-tolerance red. Chronic detection is
  # proven by its own unit/e2e tests, not smuggled into the smoke.
  "XOT_HISTORY_SAMPLE_S": "2",
  "XOT_DRIFT_PENDING_S": "600",
}


@dataclass
class FaultPhase:
  kind: str                      # "kill" | "rules" | "kill_router" (fleet holder)
  node: int                      # ring index (0 = API node; unused for kill_router)
  at_s: float                    # seconds from load start
  grace_s: float = 45.0          # how long after the fault aborts are excused
  until_s: Optional[float] = None  # rules: uninstall time (default at_s+grace)
  rules: Optional[list] = None   # rules: /v1/debug/faults payload




# Child env for ROUTER-mode replicas: bounded admission on (the gate the
# overload phase exercises) and CPU-safe latency SLO targets tight enough
# that the injected gray-failure delay (>= 2x the target) provably fires a
# burn-rate rule while healthy CI traffic stays far under them.
ROUTER_REPLICA_ENV = {
  "XOT_MAX_INFLIGHT": "1",
  "XOT_ADMIT_QUEUE_DEPTH": "2",
  "XOT_SLO_TTFT_S": "6",
  "XOT_SLO_E2E_S": "6",
  # Short trailing window for the history compact: the injected gray
  # delay pollutes the slow replica's trailing means, and a 120 s default
  # window would keep the router's differential-drift comparison naming it
  # long after the fault cleared — blocking the readmission the smoke
  # asserts. 30 s lets the gauges forget the fault on the smoke's clock.
  "XOT_DRIFT_WINDOW_S": "30",
  # Node-side drift sentinel effectively off for the smoke: the gray
  # phase is an ACUTE fault the burn rules own (and provably fire on);
  # letting the chronic sentinel also fire during it adds nothing but a
  # 60 s resolve hysteresis that outlives the run. The router-side
  # differential naming (the actuator) still runs and is what the
  # report's drift section records.
  "XOT_DRIFT_PENDING_S": "600",
}

# Extra child env for FABRIC-mode replicas (layered on the router env):
# a low prefix floor so every loadgen prompt bucket — the 8-word head
# included — clears the prefill-export / host-import minimum. The smoke
# must chain and import on its REAL traffic mix, not only the long-prompt
# tail; the host tier itself rides its default byte budget.
FABRIC_REPLICA_ENV = {
  "XOT_PREFIX_CACHE_MIN": "8",
}

# Router process env: CI-timescale cadences (1 s polls, 5 s minimum
# out-time, 2 canaries) so drain -> probe -> readmit completes inside a
# short smoke window.
ROUTER_ENV = {
  "XOT_ROUTER_POLL_S": "1",
  "XOT_ROUTER_MIN_OUT_S": "5",
  "XOT_ROUTER_PROBES": "2",
  "XOT_ROUTER_SPILL_DEPTH": "1",
  "XOT_ROUTER_PROBE_TOKENS": "2",
}

# Extra router env for FLEET mode (layered on ROUTER_ENV): CI-timescale
# elastic-controller cadences — a dead replica is declared after 3 s of
# unclean polls, queue pressure must hold 3 ticks before a scale-up, the
# actuation lease hands over 5 s after its holder dies, and spares are
# never idle-retired inside a smoke (the retire path has its own unit
# coverage; retiring mid-smoke would just shrink the fleet the hedge
# phase needs). Hedging is fully open (pct=100) with a 1.5 s floor and a
# 1x p99 factor so the injected 4 s ProcessPrompt stall provably out-waits
# the hedge delay while healthy sub-second requests never reach it.
FLEET_ROUTER_ENV = {
  "XOT_FLEET_DEAD_POLLS": "3",
  "XOT_FLEET_UP_QUEUE": "1",
  "XOT_FLEET_UP_POLLS": "3",
  "XOT_FLEET_IDLE_POLLS": "600",
  "XOT_FLEET_COOLDOWN_S": "5",
  "XOT_FLEET_LEASE_TTL_S": "5",
  "XOT_FLEET_BOOT_TIMEOUT_S": "150",
  "XOT_ROUTER_HEDGE_PCT": "100",
  "XOT_ROUTER_HEDGE_FACTOR": "1",
  "XOT_ROUTER_HEDGE_MIN_S": "1.5",
}


@dataclass
class SoakConfig:
  # Knob-backed fields read the XOT_SOAK_* registry at construction so a
  # programmatic SoakConfig() and the CLI agree on (and honor) the same
  # defaults — utils/knobs.py is the single source of truth.
  procs: int = field(default_factory=lambda: knobs.get_int("XOT_SOAK_PROCS"))
  seconds: float = field(default_factory=lambda: knobs.get_float("XOT_SOAK_SECONDS"))
  rate_rps: float = field(default_factory=lambda: knobs.get_float("XOT_SOAK_RPS"))
  arrival: str = "poisson"
  stream_fraction: float = field(
    default_factory=lambda: knobs.get_float("XOT_SOAK_STREAM_FRACTION"))
  session_reuse: float = field(
    default_factory=lambda: knobs.get_float("XOT_SOAK_SESSION_REUSE"))
  max_tokens: int = 16
  model: str = "synthetic-tiny"
  seed: int = field(default_factory=lambda: knobs.get_int("XOT_SOAK_SEED"))
  recon_tol_s: float = field(
    default_factory=lambda: knobs.get_float("XOT_SOAK_RECON_TOL_S"))
  faults: List[FaultPhase] = field(default_factory=list)
  out: Optional[str] = None
  tag: str = "run"
  api_base: int = 53510
  udp_port: int = 53530
  grpc_base: int = 53550
  log_dir: Optional[str] = None
  scrape_interval_s: float = 2.0
  drain_timeout_s: float = 120.0
  restarts: int = 1              # XOT_REQUEST_RESTARTS for the children
  alert_env: Dict[str, str] = field(default_factory=lambda: dict(SOAK_ALERT_ENV))
  # --- router mode (the replicated-rings front door) ---
  # router=True spawns `replicas` INDEPENDENT single-node rings (disjoint
  # discovery ports) plus a `python -m xotorch_tpu.router` process, and the
  # load targets the router. `overload` layers an above-capacity arrival
  # window on the base load ({"at_s", "seconds", "rate_rps"}); `gray`
  # installs a ProcessPrompt delay on one replica for a timed phase
  # ({"node", "at_s", "hold_s", "delay_s"}) — the delayed-but-health-green
  # failure the router must drain and later readmit.
  router: bool = False
  replicas: int = 2
  # fabric=True (implies router): disaggregated prefill/decode roles —
  # replica 0 boots XOT_FABRIC_ROLE=prefill (out of rotation, serves
  # kv.handles), the rest decode, peers cross-wired; the report gains a
  # `fabric` section (cross-replica import deltas + router chain counters)
  # with its own green bar (>= 1 real import, zero dropped transfers).
  fabric: bool = False
  overload: Optional[dict] = None
  gray: Optional[dict] = None
  router_port: int = 53590
  replica_env: Dict[str, str] = field(default_factory=lambda: dict(ROUTER_REPLICA_ENV))
  router_env: Dict[str, str] = field(default_factory=lambda: dict(ROUTER_ENV))
  # fleet=True (implies router): the elastic-fleet smoke. The replicas
  # spawn from a generated fleet TEMPLATE (plus `fleet_latent` latent
  # spare slots) under TWO router processes sharing one actuation lease —
  # routerA boots first and provably holds the lease, routerB carries the
  # client load. `fleet_kill_router_at_s` SIGKILLs the holder mid-load so
  # the survivor must take over actuation; the report gains a `fleet`
  # section (respawns / scale-ups / lease holders / hedge outcomes) with
  # its own green bar, including ZERO client errors total.
  fleet: bool = False
  fleet_latent: int = 1
  fleet_kill_router_at_s: Optional[float] = None
  fleet_env: Dict[str, str] = field(default_factory=lambda: dict(FLEET_ROUTER_ENV))


class SoakRing:
  """Child processes + the last-good scrape of each (a killed node's final
  truth is its last successful scrape)."""

  def __init__(self, cfg: SoakConfig):
    self.cfg = cfg
    self.procs: Dict[str, object] = {}
    self.logs: Dict[str, object] = {}
    self.ports: Dict[str, int] = {}
    # Router mode: N independent single-node rings, named rep<i>; the node
    # id doubles as the replica id everywhere (metrics, cluster views).
    self.names: List[str] = ([f"rep{i}" for i in range(cfg.replicas)] if cfg.router
                             else [f"soak-{i}" for i in range(cfg.procs)])
    # Fleet mode: latent template slots the controller may scale into.
    # They are not harness children — everything that must also cover
    # controller-spawned processes (scrapes, drain, leak check, teardown)
    # iterates all_names and resolves liveness via the pid sidecar.
    self.latent_names: List[str] = (
      [f"rep{cfg.replicas + i}" for i in range(cfg.fleet_latent)]
      if cfg.fleet else [])
    self.all_names: List[str] = self.names + self.latent_names
    self.router_proc = None
    self.router_log = None
    self.last_router: Optional[dict] = None
    # Fleet mode: the second (lease-holding) router process, the last-good
    # /v1/router body PER router id (a dead holder's final counters must
    # survive its death), and every lease holder_id ever observed.
    self.fleet_router_proc = None
    self.fleet_router_log = None
    self.fleet_template: Optional[Path] = None
    self.fleet_status: Dict[str, dict] = {}
    self.fleet_holders: set = set()
    # Out-of-rotation routing tracker, per EPISODE: while the router
    # reports a replica draining/probing, its routed_total is baselined at
    # the episode's first scrape and any growth accumulates into `accum`
    # when the episode closes (replica healthy again). Episode-scoped so
    # requests legitimately routed BETWEEN two drains (replica healthy)
    # never count as routed-while-out. accum + the live episode's delta
    # > 0 means traffic landed on a drained replica — the failover red.
    self.router_track: Dict[str, Dict[str, Optional[int]]] = {}
    self.last_metrics: Dict[str, Dict[str, float]] = {}
    self.last_flight: Dict[str, dict] = {}
    self.last_cluster: Optional[dict] = None
    self.last_perf: Optional[dict] = None
    self.last_alerts: Optional[dict] = None
    self.last_anatomy: Optional[dict] = None
    # Latest /v1/history body per head node: the chronic-memory record the
    # report's history section summarizes and CI uploads as an artifact.
    self.last_history: Dict[str, dict] = {}
    # Where children spool their flight ring on SIGTERM (teardown): a
    # terminated node's evidence survives the process instead of relying
    # only on its last-good scrape. Set by spawn().
    self.dump_dir: Optional[Path] = None
    # Firing rows accumulated across every /v1/alerts scrape, keyed by
    # alert identity: peer eviction PRUNES a dead node's compact from
    # later scrapes, so the settle scrape alone could lose a firing that
    # happened on it — the verdict classifies this superset instead.
    self.alert_rows: Dict[tuple, dict] = {}
    self.killed: set = set()

  def spawn(self, log_dir: Path) -> None:
    import subprocess
    import sys as _sys
    from tests.xproc_harness import node_env, spawn_node
    self.dump_dir = log_dir / "flight_dumps"
    self.dump_dir.mkdir(parents=True, exist_ok=True)
    for i, name in enumerate(self.names):
      self.ports[name] = self.cfg.api_base + i
      self.logs[name] = open(log_dir / f"{name}.log", "w")
      # Router mode gives every replica a DISJOINT discovery port pair so
      # the "replicas" stay independent rings instead of gossiping into one.
      udp = self.cfg.udp_port + (2 * i if self.cfg.router else 0)
      extra = {"XOT_REQUEST_RESTARTS": str(self.cfg.restarts),
               "XOT_FLIGHT_DUMP_DIR": str(self.dump_dir),
               **self.cfg.alert_env}
      if self.cfg.router:
        extra.update(self.cfg.replica_env)
      if self.cfg.fleet:
        # Persistent jit cache: the template slots carry the same knob, so
        # a controller respawn lands on compiles this very warmup paid —
        # the "warm cold-start" the fleet smoke soft-verifies.
        extra["XOT_COMPILE_CACHE_DIR"] = os.environ.get(
          "JAX_COMPILATION_CACHE_DIR", "/root/.cache/xot_jax_cache")
      if self.cfg.fabric:
        # Disaggregated roles: replica 0 prefills and offers, the rest
        # decode. Peers are cross-wired so an entry fetch resolves by URL
        # even when the offer path is not what found it.
        peers = ",".join(f"http://127.0.0.1:{self.cfg.api_base + j}"
                         for j in range(len(self.names)) if j != i)
        extra.update({"XOT_FABRIC_ROLE": "prefill" if i == 0 else "decode",
                      "XOT_FABRIC_PEERS": peers,
                      **FABRIC_REPLICA_ENV})
      self.procs[name] = spawn_node(
        name, self.cfg.api_base + i, udp, udp,
        self.cfg.grpc_base + i, self.logs[name], model=self.cfg.model,
        response_timeout=180, extra_env=extra,
      )
    if self.cfg.fleet:
      for j, name in enumerate(self.latent_names):
        self.ports[name] = self.cfg.api_base + len(self.names) + j
      self._write_fleet_template(log_dir, extra)
      self._spawn_fleet_routers(log_dir)
    elif self.cfg.router:
      self.router_log = open(log_dir / "router.log", "w")
      replica_flags = []
      for name in self.names:
        replica_flags += ["--replica", f"http://127.0.0.1:{self.ports[name]}"]
      self.router_proc = subprocess.Popen(
        [_sys.executable, "-m", "xotorch_tpu.router",
         "--port", str(self.cfg.router_port), *replica_flags],
        env=node_env(**self.cfg.router_env), stdout=self.router_log,
        stderr=subprocess.STDOUT)

  def _node_argv(self, name: str, i: int) -> List[str]:
    """The exact argv spawn_node would use for slot i — a controller
    respawn must reproduce the harness spawn bit-for-bit (same ports, same
    discovery isolation) or the 'respawned' replica is a different ring."""
    udp = self.cfg.udp_port + 2 * i
    return [sys.executable, "-m", "xotorch_tpu.main",
            "--node-id", name, "--disable-tui",
            "--inference-engine", "jax",
            "--default-model", self.cfg.model,
            "--chatgpt-api-port", str(self.cfg.api_base + i),
            "--listen-port", str(udp), "--broadcast-port", str(udp),
            "--node-port", str(self.cfg.grpc_base + i),
            "--discovery-timeout", "15",
            "--chatgpt-api-response-timeout", "180"]

  def _write_fleet_template(self, log_dir: Path, node_extra: Dict[str, str]) -> None:
    """The slot universe both routers load: harness replicas as active
    slots, spares as latent ones. Slot env is the FULL node environment
    (not a delta) so a spawn from inside a router process cannot inherit
    router-only knobs. The pid sidecar is pre-seeded with the harness
    children's pids — that is how the controller SIGKILLs a half-dead
    replica before respawning and how teardown finds controller spawns."""
    from tests.xproc_harness import node_env
    active = set(self.names)
    slots = []
    for i, name in enumerate(self.all_names):
      slots.append({
        "name": name,
        "url": f"http://127.0.0.1:{self.ports[name]}",
        "active": name in active,
        "argv": self._node_argv(name, i),
        "env": node_env(**node_extra),
        "log": str(log_dir / f"{name}.log"),
      })
    self.fleet_template = log_dir / "fleet_template.json"
    self.fleet_template.write_text(json.dumps({"slots": slots}, indent=1) + "\n")
    Path(str(self.fleet_template) + ".pids").write_text(
      json.dumps({name: self.procs[name].pid for name in self.names}) + "\n")

  def _spawn_fleet_routers(self, log_dir: Path) -> None:
    """routerA first, and it must HOLD the lease before routerB even
    boots: the holder-kill phase then provably hands actuation over
    instead of flaking on whichever router won the boot race."""
    import subprocess
    from tests.xproc_harness import node_env, wait_for
    renv = node_env(**{**self.cfg.router_env, **self.cfg.fleet_env,
                       "XOT_FLEET_LEASE_PATH": str(log_dir / "fleet.lease")})

    def router(rid: str, port: int, log):
      return subprocess.Popen(
        [sys.executable, "-m", "xotorch_tpu.router",
         "--port", str(port), "--fleet-template", str(self.fleet_template),
         "--router-id", rid],
        env=renv, stdout=log, stderr=subprocess.STDOUT)

    self.fleet_router_log = open(log_dir / "routerA.log", "w")
    self.fleet_router_proc = router(
      "routerA", self.cfg.router_port + 1, self.fleet_router_log)

    def a_holds() -> bool:
      st = self.get_json_port(self.cfg.router_port + 1, "/v1/router")
      lease = ((st or {}).get("fleet") or {}).get("lease") or {}
      return bool(lease.get("held"))

    wait_for(a_holds, 60, "routerA holds the fleet lease",
             proc=self.fleet_router_proc,
             log_path=getattr(self.fleet_router_log, "name", None))
    self.router_log = open(log_dir / "routerB.log", "w")
    self.router_proc = router("routerB", self.cfg.router_port, self.router_log)

  def _fleet_pids(self) -> Dict[str, int]:
    if not self.fleet_template:
      return {}
    try:
      doc = json.loads(Path(str(self.fleet_template) + ".pids").read_text())
    except (OSError, ValueError):
      return {}
    if not isinstance(doc, dict):
      return {}
    out = {}
    for name, pid in doc.items():
      try:
        out[str(name)] = int(pid)
      except (TypeError, ValueError):
        continue
    return out

  def wait_ready(self) -> None:
    from tests.xproc_harness import http_get, wait_for
    for name in self.names:
      port = self.ports[name]
      wait_for(lambda p=port: http_get(p, "/healthcheck").get("status") == "ok",
               180, f"{name} API health", proc=self.procs[name],
               log_path=self._log_path(name))
    # Router mode: each replica is its own 1-node ring; plain mode: every
    # node must see the full ring.
    n = 1 if self.cfg.router else len(self.names)
    for name in self.names:
      port = self.ports[name]
      wait_for(lambda p=port: len(http_get(p, "/v1/topology").get("nodes", {})) == n,
               120, f"{name} sees {n}-node ring", proc=self.procs[name],
               log_path=self._log_path(name))
    if self.cfg.router:
      # Fabric mode deliberately keeps the prefill replica OUT of rotation,
      # so the router advertises one fewer routable replica — and the chain
      # path needs it discovered AS prefill before any load arrives.
      want = len(self.names) - (1 if self.cfg.fabric else 0)
      wait_for(lambda: http_get(self.cfg.router_port, "/healthcheck")
               .get("routable") == want,
               60, f"router routes {want} of {len(self.names)} replicas",
               proc=self.router_proc,
               log_path=getattr(self.router_log, "name", None))
      if self.cfg.fabric:
        wait_for(lambda: len(http_get(self.cfg.router_port, "/v1/router")
                             .get("prefill_replicas") or []) >= 1,
                 60, "router discovers the prefill replica",
                 proc=self.router_proc,
                 log_path=getattr(self.router_log, "name", None))
      if self.cfg.fleet:
        # The holder router is warmed too (its recent-body ring feeds the
        # respawn pre-announce), so it must also route everything first.
        wait_for(lambda: http_get(self.cfg.router_port + 1, "/healthcheck")
                 .get("routable") == want,
                 60, f"routerA routes {want} of {len(self.names)} replicas",
                 proc=self.fleet_router_proc,
                 log_path=getattr(self.fleet_router_log, "name", None))

  def _log_path(self, name: str):
    f = self.logs.get(name)
    return getattr(f, "name", None)

  def alive(self, name: str) -> bool:
    proc = self.procs.get(name)
    if proc is not None and proc.poll() is None and name not in self.killed:
      return True
    # Fleet mode: a respawned or scaled-up replica is the ROUTER's child,
    # not ours — the spawner's pid sidecar is the only liveness truth. The
    # poll() above has already reaped our own SIGKILLed child, so a stale
    # sidecar pid answers ESRCH here rather than lingering as a zombie.
    if self.cfg.fleet:
      pid = self._fleet_pids().get(name)
      if pid:
        try:
          os.kill(pid, 0)
          return True
        except OSError:
          return False
    return False

  def get_json(self, name: str, path: str, timeout: float = 5.0) -> Optional[dict]:
    return self.get_json_port(self.ports[name], path, timeout)

  def get_json_port(self, port: int, path: str, timeout: float = 5.0) -> Optional[dict]:
    try:
      with urllib.request.urlopen(
          f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read())
    except Exception:
      return None

  def get_text(self, name: str, path: str, timeout: float = 5.0) -> Optional[str]:
    try:
      with urllib.request.urlopen(
          f"http://127.0.0.1:{self.ports[name]}{path}", timeout=timeout) as r:
        return r.read().decode("utf-8", "replace")
    except Exception:
      return None

  def scrape_once(self) -> None:
    for name in self.all_names:
      if not self.alive(name):
        continue
      text = self.get_text(name, "/metrics")
      if text is not None:
        self.last_metrics[name] = parse_prom(text)
      flight = self.get_json(name, "/v1/debug/flight")
      if flight is not None:
        self.last_flight[name] = flight
    # Cluster/alert rollups. A plain ring's node 0 sees every peer via the
    # status bus; router-mode replicas are DISJOINT rings, so each head is
    # scraped and the node rows merged into one cluster/alert view (node
    # ids are unique across replicas by construction).
    heads = [n for n in (self.all_names if self.cfg.router else self.names[:1])
             if self.alive(n)]
    merged_cluster: Dict[str, dict] = {}
    merged_alert_nodes: Dict[str, dict] = {}
    for head in heads:
      cluster = self.get_json(head, "/v1/cluster/metrics")
      if cluster is not None:
        merged_cluster.update(cluster.get("nodes") or {})
      alerts = self.get_json(head, "/v1/alerts")
      if alerts is not None:
        merged_alert_nodes.update(alerts.get("nodes") or {})
      # ?window=0: the stats/trailing head of the record without its rows
      # — the continuous scrape only feeds the report's summary, so
      # shipping every retained row each tick would be discarded I/O. The
      # full body is fetched ONCE at settle (scrape_history_full) for the
      # history_settle.json artifact.
      history = self.get_json(head, "/v1/history?window=0")
      if history is not None:
        self.last_history[head] = history
    if merged_cluster:
      self.last_cluster = {"nodes": merged_cluster, "count": len(merged_cluster)}
    if merged_alert_nodes:
      self.last_alerts = {
        "nodes": merged_alert_nodes,
        "cluster": {"firing": sum(int(a.get("firing") or 0)
                                  for a in merged_alert_nodes.values())},
      }
      for row in verdicts.alert_rows_of(self.last_alerts):
        key = verdicts.alert_row_key(row)
        prev = self.alert_rows.get(key)
        if prev is None or (row.get("resolved_at") is not None
                            and prev.get("resolved_at") is None):
          self.alert_rows[key] = row
    if heads:
      perf = self.get_json(heads[0], "/v1/perf")
      if perf is not None:
        self.last_perf = perf
      # The origin's latency-anatomy rollup: stage-contribution
      # percentiles over its reservoir of skew-corrected breakdowns.
      anatomy = self.get_json(heads[0], "/v1/anatomy")
      if anatomy is not None:
        self.last_anatomy = anatomy
    if self.cfg.router and self.router_proc is not None and self.router_proc.poll() is None:
      status = self.get_json_port(self.cfg.router_port, "/v1/router")
      if status is not None:
        self.last_router = status
        self._note_fleet(status)
        for name, row in (status.get("replicas") or {}).items():
          # Fleet boot/retire phases are out-of-rotation too: routing to a
          # replica the controller is still warming (or tearing down) is
          # the same red as routing to a drained one.
          state = ("retiring" if row.get("retiring")
                   else "warming" if row.get("warming")
                   else str(row.get("state") or ""))
          self.note_router_row(name, state, int(row.get("routed_total") or 0))
    if (self.cfg.fleet and self.fleet_router_proc is not None
        and self.fleet_router_proc.poll() is None):
      status = self.get_json_port(self.cfg.router_port + 1, "/v1/router")
      if status is not None:
        self._note_fleet(status)

  def _note_fleet(self, status: dict) -> None:
    """Last-good /v1/router per router id + the holder set. Keyed by the
    router's own id so the holder's final pre-death counters (its respawn
    actuations) keep contributing after it is SIGKILLed."""
    if not isinstance(status.get("fleet"), dict):
      return
    self.fleet_status[str(status.get("router_id") or "?")] = status
    lease = (status.get("fleet") or {}).get("lease") or {}
    if lease.get("held") and lease.get("holder_id"):
      self.fleet_holders.add(str(lease["holder_id"]))

  def scrape_history_full(self) -> None:
    """One full /v1/history fetch per reachable head (every retained row)
    — the settle-time artifact the CI step uploads; the continuous scrape
    deliberately fetches only the row-less summary."""
    heads = [n for n in (self.all_names if self.cfg.router else self.names[:1])
             if self.alive(n)]
    for head in heads:
      history = self.get_json(head, "/v1/history", timeout=10.0)
      if history is not None:
        self.last_history[head] = history

  def note_router_row(self, name: str, state: str, routed: int) -> None:
    """One router-scrape observation into the out-of-rotation tracker."""
    track = self.router_track.setdefault(
      name, {"accum": 0, "episode_start": None, "episode_last": None})
    if state in ("draining", "probing", "warming", "retiring"):
      if track["episode_start"] is None:
        track["episode_start"] = routed
      track["episode_last"] = routed
    elif track["episode_start"] is not None:
      # Episode closed (readmitted): bank its delta, reset the baseline.
      track["accum"] += max(
        0, int(track["episode_last"] or track["episode_start"])
        - int(track["episode_start"]))
      track["episode_start"] = track["episode_last"] = None

  def kill(self, index: int) -> None:
    name = self.names[index]
    proc = self.procs.get(name)
    if proc is not None and proc.poll() is None:
      proc.send_signal(signal.SIGKILL)
    self.killed.add(name)

  def kill_fleet_router(self) -> None:
    """SIGKILL the holder router (routerA — spawn() serialized its lease
    acquisition) so the surviving load router must take over actuation."""
    if self.fleet_router_proc is not None and self.fleet_router_proc.poll() is None:
      self.fleet_router_proc.send_signal(signal.SIGKILL)

  def teardown(self) -> None:
    from tests.xproc_harness import teardown_nodes
    procs = dict(self.procs)
    logs = dict(self.logs)
    if self.router_proc is not None:
      procs["router"] = self.router_proc
      if self.router_log is not None:
        logs["router"] = self.router_log
    if self.fleet_router_proc is not None:
      procs["routerA"] = self.fleet_router_proc
      if self.fleet_router_log is not None:
        logs["routerA"] = self.fleet_router_log
    teardown_nodes(procs, logs)
    self._teardown_fleet_pids()

  def _teardown_fleet_pids(self) -> None:
    """Controller-spawned replicas (respawns, scale-ups) are children of a
    ROUTER process, not ours; the routers are already down, so the pid
    sidecar the spawner maintains is the handover. SIGTERM first so they
    spool their flight rings (XOT_FLIGHT_DUMP_DIR is in the slot env),
    SIGKILL whatever ignores it. Idempotent: dead pids answer ESRCH."""
    ours = {proc.pid for proc in self.procs.values()}
    pids = [pid for pid in self._fleet_pids().values() if pid not in ours]
    for pid in pids:
      try:
        os.kill(pid, signal.SIGTERM)
      except OSError:
        pass
    deadline = time.monotonic() + 8.0
    while time.monotonic() < deadline:
      if not any(_pid_alive(pid) for pid in pids):
        return
      time.sleep(0.2)
    for pid in pids:
      try:
        os.kill(pid, signal.SIGKILL)
      except OSError:
        pass

  def collect_flight_dumps(self) -> Dict[str, dict]:
    """Parse the post-mortem spool: {node_id: dump} from every
    `flight_*.json` a SIGTERM'd child wrote to the dump dir. Children dump
    at teardown (and on any external SIGTERM); a SIGKILLed node can write
    nothing — its last-good scrape stays its only record."""
    return collect_flight_dumps(self.dump_dir)


def _pid_alive(pid: int) -> bool:
  try:
    os.kill(pid, 0)
    return True
  except OSError:
    return False


def collect_flight_dumps(dump_dir: Optional[Path]) -> Dict[str, dict]:
  out: Dict[str, dict] = {}
  if not dump_dir:
    return out
  for path in sorted(Path(dump_dir).glob("flight_*.json")):
    try:
      dump = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
      continue
    node_id = dump.get("node_id")
    if node_id:
      out[str(node_id)] = dump
  return out


def _sum_counter(metrics_by_node: Dict[str, Dict[str, float]], name: str) -> float:
  return sum(float(m.get(name, 0.0)) for m in metrics_by_node.values())


def _abort_events(flight_by_node: Dict[str, dict]) -> List[dict]:
  """Watchdog/deadline abort evidence from each node's frozen snapshots:
  one event per snapshot whose timeline contains a watchdog.fired or
  deadline.expired transition, stamped with the snapshot freeze time."""
  events = []
  for node_id, flight in flight_by_node.items():
    for snap in flight.get("snapshots") or []:
      names = {e.get("event") for e in snap.get("events") or []}
      if "watchdog.fired" in names or "deadline.expired" in names:
        events.append({"node_id": node_id, "ts": snap.get("frozen_at"),
                       "request_id": snap.get("request_id"),
                       "reason": snap.get("reason")})
  return events


async def _chat_once(port: int, model: str, timeout_s: float = 300.0) -> None:
  """One sequential warmup completion (pays the cold-jit compiles before
  the measured window opens)."""
  import aiohttp
  body = {"model": model, "messages": [{"role": "user", "content": "soak warmup"}],
          "max_tokens": 8, "temperature": 0}
  async with aiohttp.ClientSession(
      timeout=aiohttp.ClientTimeout(total=timeout_s)) as session:
    async with session.post(f"http://127.0.0.1:{port}/v1/chat/completions",
                            json=body) as resp:
      text = await resp.text()
      if resp.status != 200:
        raise RuntimeError(f"warmup failed ({resp.status}): {text[:300]}")


async def _scraper(ring: SoakRing, stop: asyncio.Event) -> None:
  loop = asyncio.get_running_loop()
  while not stop.is_set():
    await loop.run_in_executor(None, ring.scrape_once)
    try:
      await asyncio.wait_for(stop.wait(), timeout=ring.cfg.scrape_interval_s)
    except asyncio.TimeoutError:
      pass


async def _fault_driver(ring: SoakRing, t_load_start: float,
                        windows: List[dict]) -> None:
  """Execute the wall-clock fault schedule; records each phase's excuse
  window (unix seconds) for the verdict's abort classification."""
  phases = sorted(ring.cfg.faults, key=lambda p: p.at_s)
  loop = asyncio.get_running_loop()
  for phase in phases:
    delay = t_load_start + phase.at_s - time.monotonic()
    if delay > 0:
      await asyncio.sleep(delay)
    now = time.time()
    try:
      if phase.kind == "kill":
        ring.kill(phase.node)
        windows.append({"kind": "kill", "node": ring.names[phase.node],
                        "t0": now - 1.0, "t1": now + phase.grace_s})
      elif phase.kind == "kill_router":
        # HA handover: no client impact is EXPECTED (the load router
        # survives), so the short grace window exists only to make the
        # phase visible in the report's fault timeline.
        ring.kill_fleet_router()
        windows.append({"kind": "kill_router", "node": "routerA",
                        "t0": now - 1.0, "t1": now + phase.grace_s})
      elif phase.kind == "rules":
        name = ring.names[phase.node]
        until = phase.until_s if phase.until_s is not None else phase.at_s + phase.grace_s
        body = json.dumps({"rules": phase.rules or []}).encode()

        def post(payload=body, port=ring.ports[name]):
          req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/debug/faults", data=payload,
            headers={"Content-Type": "application/json"})
          with urllib.request.urlopen(req, timeout=5.0):
            pass

        def delete(port=ring.ports[name]):
          req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/debug/faults", method="DELETE")
          with urllib.request.urlopen(req, timeout=5.0):
            pass

        try:
          await loop.run_in_executor(None, post)
          windows.append({"kind": "rules", "node": name,
                          "t0": now - 1.0, "t1": time.time() + (until - phase.at_s) + phase.grace_s})
          hold = t_load_start + until - time.monotonic()
          if hold > 0:
            await asyncio.sleep(hold)
        finally:
          # Synchronous on purpose: this must also run when the driver is
          # CANCELLED mid-hold (teardown after an early load failure), and
          # a cancelled coroutine cannot await the executor. Localhost with
          # a 5 s timeout; a killed/unreachable node has no injector left
          # to remove.
          try:
            delete()
          except Exception:
            pass
    except asyncio.CancelledError:
      raise
    except Exception as e:
      # One unreachable/late node must not lose the whole soak (the run's
      # collected data and verdict): record the failed phase, keep going.
      print(f"soak: fault phase {phase.kind}@{phase.at_s:g} (node {phase.node}) "
            f"failed: {e!r}", file=sys.stderr)


async def _drain(ring: SoakRing, timeout_s: float) -> bool:
  """Wait until every reachable node reports zero in-flight requests."""
  deadline = time.monotonic() + timeout_s
  loop = asyncio.get_running_loop()
  while time.monotonic() < deadline:
    await loop.run_in_executor(None, ring.scrape_once)
    busy = [n for n in ring.all_names if ring.alive(n)
            and float(ring.last_metrics.get(n, {}).get("xot_active_requests", 0.0)) > 0]
    if not busy:
      return True
    await asyncio.sleep(1.0)
  return False


async def run_soak(cfg: SoakConfig) -> dict:
  """The whole arc: spawn -> warm -> baseline -> load + faults + scrapes ->
  drain -> settle scrapes -> verdict report (returned AND written to
  cfg.out when set)."""
  import tempfile
  log_dir = Path(cfg.log_dir) if cfg.log_dir else Path(tempfile.mkdtemp(prefix="xot_soak_"))
  log_dir.mkdir(parents=True, exist_ok=True)
  if cfg.fabric:
    # Disaggregated roles only make sense behind the front door: the
    # router is what chains prefill -> offer -> decode per request.
    cfg.router = True
  if cfg.fleet:
    # The elastic fleet lives behind routers by construction.
    cfg.router = True
    if cfg.fleet_kill_router_at_s is not None:
      cfg.faults.append(FaultPhase(
        kind="kill_router", node=0,
        at_s=float(cfg.fleet_kill_router_at_s), grace_s=10.0))
  if cfg.gray is not None:
    # The gray-failure drain phase: a timed ProcessPrompt delay on one
    # replica — requests there get slower (visible to ITS burn-rate rules
    # and to clients) while /healthcheck stays green. Rides the existing
    # rules-phase machinery, so its window excuses the resulting alert
    # firings exactly like any injected fault.
    g = cfg.gray
    cfg.faults.append(FaultPhase(
      kind="rules", node=int(g.get("node", cfg.replicas - 1)),
      at_s=float(g["at_s"]), until_s=float(g["at_s"]) + float(g.get("hold_s", 20.0)),
      grace_s=float(g.get("grace_s", 60.0)),
      rules=[{"rpc": "ProcessPrompt", "action": "delay", "nth": 1,
              "times": 1000000, "delay_s": float(g.get("delay_s", 12.0))}]))
  ring = SoakRing(cfg)
  t_wall_start = time.time()
  loop = asyncio.get_running_loop()
  try:
    await loop.run_in_executor(None, ring.spawn, log_dir)
    await loop.run_in_executor(None, ring.wait_ready)
    if cfg.router:
      # Pay every replica's cold jit directly, then prove the router path.
      for name in ring.names:
        await _chat_once(ring.ports[name], cfg.model)
      if cfg.fleet:
        # Warm the holder router too: its recent-body ring is what feeds
        # a respawned replica's warm pre-announce.
        await _chat_once(cfg.router_port + 1, cfg.model)
      api_port = cfg.router_port
    else:
      api_port = ring.ports[ring.names[0]]
    await _chat_once(api_port, cfg.model)
    # Let the warmup's metric summaries ride one topology tick so the
    # baseline cluster scrape includes every node's post-warmup counters.
    await asyncio.sleep(5.0)
    await loop.run_in_executor(None, ring.scrape_once)
    base_cluster = (ring.last_cluster or {}).get("nodes", {})
    base_metrics = {n: dict(m) for n, m in ring.last_metrics.items()}
    # Router baseline at load start: boot-time/warmup drains (cold-jit
    # alerts, a poll racing a replica's bind) resolved before the measured
    # window must not satisfy the gray-failure drain/readmit expectation —
    # and the routed-while-out tracker starts fresh for the same reason.
    base_router = dict(ring.last_router) if ring.last_router else None
    ring.router_track.clear()
    # Fleet baselines at load start, same reasoning: boot-time lease churn
    # and warmup-era actuations (none expected, but races exist) must not
    # satisfy the measured window's respawn/scale-up/holder expectations.
    base_fleet = {rid: st for rid, st in ring.fleet_status.items()}
    ring.fleet_holders.clear()

    plan = LoadPlan(seconds=cfg.seconds, rate_rps=cfg.rate_rps, arrival=cfg.arrival,
                    stream_fraction=cfg.stream_fraction, session_reuse=cfg.session_reuse,
                    max_tokens=cfg.max_tokens, model=cfg.model, seed=cfg.seed,
                    extra_phases=[dict(cfg.overload)] if cfg.overload else [])
    stop_scraper = asyncio.Event()
    scraper = asyncio.ensure_future(_scraper(ring, stop_scraper))
    windows: List[dict] = []
    t_load_start = time.monotonic()
    t_wall_load_start = time.time()
    fault_task = asyncio.ensure_future(_fault_driver(ring, t_load_start, windows))
    try:
      records = await run_load(api_port, plan)
    finally:
      # Cancel rather than await: in the normal arc every phase fires
      # within the load window so this is a no-op, but a load that died
      # early must not block teardown for the rest of a long wall-clock
      # fault schedule. The driver's own cleanup (rules uninstall) is
      # cancel-safe.
      if not fault_task.done():
        fault_task.cancel()
      await asyncio.gather(fault_task, return_exceptions=True)
      drained = await _drain(ring, cfg.drain_timeout_s)
      # Two topology ticks so surviving peers' final summaries reach node 0.
      await asyncio.sleep(5.0)
      stop_scraper.set()
      await scraper
    await loop.run_in_executor(None, ring.scrape_once)
    settle_a = {n: dict(m) for n, m in ring.last_metrics.items() if ring.alive(n)}
    await asyncio.sleep(3.0)
    await loop.run_in_executor(None, ring.scrape_once)
    settle_b = {n: dict(m) for n, m in ring.last_metrics.items() if ring.alive(n)}
    # Settle-time /v1/alerts scrape: the firing->resolved evidence the CI
    # step uploads as an artifact (and the report's alert section reads).
    try:
      (log_dir / "alerts_settle.json").write_text(
        json.dumps(ring.last_alerts or {}, indent=1) + "\n")
    except OSError as e:
      print(f"soak: writing alerts_settle.json failed: {e!r}", file=sys.stderr)
    # The history record next to the alerts scrape: the same CI step
    # uploads both, so a chronic-rot investigation has the full
    # downsampled time-series, not just the report's trailing means.
    try:
      await loop.run_in_executor(None, ring.scrape_history_full)
      (log_dir / "history_settle.json").write_text(
        json.dumps(ring.last_history or {}, indent=1) + "\n")
    except OSError as e:
      print(f"soak: writing history_settle.json failed: {e!r}", file=sys.stderr)

    # Tear the ring down BEFORE assembling the report: children spool
    # their flight rings on SIGTERM (XOT_FLIGHT_DUMP_DIR), and the dumps
    # are post-mortem evidence the report merges with the last-good
    # scrapes. The finally-teardown below is then an idempotent no-op.
    await loop.run_in_executor(None, ring.teardown)
    dumps = ring.collect_flight_dumps()

    report = _build_report(cfg, ring, records, windows, base_cluster, base_metrics,
                           settle_a, settle_b, drained, t_wall_start, dumps=dumps,
                           t_wall_load_start=t_wall_load_start,
                           base_router=base_router, base_fleet=base_fleet)
    verdicts.evaluate(report)
    if cfg.out:
      verdicts.write_report(report, cfg.out)
    return report
  finally:
    await loop.run_in_executor(None, ring.teardown)


def _build_report(cfg: SoakConfig, ring: SoakRing, records, windows,
                  base_cluster, base_metrics, settle_a, settle_b,
                  drained: bool, t_wall_start: float,
                  dumps: Optional[Dict[str, dict]] = None,
                  t_wall_load_start: Optional[float] = None,
                  base_router: Optional[dict] = None,
                  base_fleet: Optional[Dict[str, dict]] = None) -> dict:
  ok_recs = [r for r in records if r.ok]
  rejected_recs = [r for r in records if getattr(r, "rejected", False)]
  # 429s are deliberate admission sheds, not failures: they never reached
  # the ring, so they belong to neither the error count nor the e2e
  # reconciliation sample (the server only times requests it ADMITTED).
  err_recs = [r for r in records if not r.ok and not getattr(r, "rejected", False)]
  # The server's request_seconds family records "any outcome" (finish OR
  # abort), so the client e2e sample it reconciles against must count
  # errored requests too — excluding them would compare a survivors-only
  # distribution against an everyone distribution.
  e2e_all = [r.e2e_s for r in records
             if r.e2e_s is not None and not getattr(r, "rejected", False)]

  def in_window(rec) -> bool:
    t_fail = rec.t_submit + (rec.e2e_s or 0.0)
    return any(w["t0"] <= t_fail <= w["t1"] for w in windows)

  errors_outside = [r for r in err_recs if not in_window(r)]
  elapsed = max(1e-9, time.time() - t_wall_start)
  client = {
    "submitted": len(records),
    "ok": len(ok_recs),
    "rejected": len(rejected_recs),
    "errors": len(err_recs),
    "errors_in_fault_windows": len(err_recs) - len(errors_outside),
    "errors_outside_fault_windows": len(errors_outside),
    "streamed": sum(1 for r in records if r.streamed),
    "session_reuse": sum(1 for r in records if r.session is not None),
    "rps_target": cfg.rate_rps,
    "rps_achieved": round(len(records) / cfg.seconds, 4) if cfg.seconds else None,
    "ttft_s": verdicts.latency_summary([r.ttft_s for r in ok_recs if r.ttft_s is not None]),
    # Raw per-gap samples, not per-request means: the server's
    # token_seconds family is per-token, so the client sample must be too.
    "tpot_s": verdicts.latency_summary(
      [g for r in ok_recs for g in (getattr(r, "tpot_gaps", None) or [])]),
    "tpot_request_mean_s": verdicts.latency_summary(
      [r.tpot_s for r in ok_recs if r.tpot_s is not None]),
    "e2e_s": verdicts.latency_summary(e2e_all),
    "e2e_ok_s": verdicts.latency_summary([r.e2e_s for r in ok_recs if r.e2e_s is not None]),
    "error_samples": [r.error for r in err_recs[:5]],
  }

  nodes_final = (ring.last_cluster or {}).get("nodes", {})
  # Node ids == spawn names; names[0] runs the API. Router runs have one
  # origin PER replica (each head node's first touch ≈ HTTP arrival there).
  origin = set(ring.all_names) if cfg.router else ring.names[0]
  server = {}
  for family, _client_key, mode in verdicts.RECONCILE_FAMILIES:
    # Two-sided families compare like with like: only the ORIGIN node's
    # histogram (its first touch ≈ HTTP arrival) — the ring-merged family
    # is a mixture of per-node views of the same request. One-sided
    # families merge ring-wide (the invariant holds for every view).
    only = origin if mode == "two_sided" else None
    server[family] = verdicts.server_percentiles(
      nodes_final, base_cluster, family, only_node=only)
  for counter, prom in (
      ("watchdog_aborts", "xot_watchdog_aborts_total"),
      ("request_restarts", "xot_request_restarts_total"),
      ("peer_evictions", "xot_peer_evictions_total"),
      ("dedup_drops", "xot_dedup_drops_total"),
      ("hop_retries", "xot_hop_retries_total"),
      ("admission_rejections", "xot_admission_rejections_total"),
      ("requests", "xot_requests_total"),
      ("tokens", "xot_tokens_total"),
  ):
    server[counter] = (_sum_counter(ring.last_metrics, prom)
                       - _sum_counter(base_metrics, prom))
  if ring.last_perf is not None:
    server["perf"] = {k: ring.last_perf.get(k) for k in ("gauges", "dispatch") if k in ring.last_perf}

  # Abort evidence: last-good scrapes MERGED with the post-mortem dumps —
  # a terminated node's frozen snapshots survive teardown even when its
  # final scrape was missed (killed nodes still rely on last-good).
  flight_evidence = {n: dict(f) for n, f in ring.last_flight.items()}
  for node_id, dump in (dumps or {}).items():
    row = flight_evidence.setdefault(node_id, {})
    have = {(s.get("request_id"), s.get("reason"), s.get("frozen_at"))
            for s in row.get("snapshots") or []}
    merged = list(row.get("snapshots") or [])
    for snap in dump.get("snapshots") or []:
      key = (snap.get("request_id"), snap.get("reason"), snap.get("frozen_at"))
      if key not in have:
        merged.append(snap)
    row["snapshots"] = merged
  events = _abort_events(flight_evidence)
  aborts = verdicts.classify_aborts(events, windows)
  aborts["unattributed"] = max(0, int(server["watchdog_aborts"]) - len(events))
  # Classify the accumulated superset, not just the settle scrape: a
  # firing on a since-evicted peer survives here even though its compact
  # no longer rides the final /v1/alerts response. SLO burns and
  # perf_drift firings split into their own sections — different green
  # bars, different benchdiff zero-tolerance keys.
  all_rows = list(ring.alert_rows.values())
  alerts = verdicts.classify_alert_firings(
    [r for r in all_rows if not verdicts.is_drift_row(r)], windows,
    since=t_wall_load_start)
  drift = verdicts.summarize_drift(
    [r for r in all_rows if verdicts.is_drift_row(r)], windows,
    since=t_wall_load_start, router_status=ring.last_router)

  report = {
    "schema": verdicts.SCHEMA,
    "tag": cfg.tag,
    "recorded": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_wall_start)),
    "elapsed_s": round(time.time() - t_wall_start, 1),
    "config": {
      "procs": cfg.procs, "seconds": cfg.seconds, "rate_rps": cfg.rate_rps,
      "arrival": cfg.arrival, "stream_fraction": cfg.stream_fraction,
      "session_reuse": cfg.session_reuse, "max_tokens": cfg.max_tokens,
      "model": cfg.model, "seed": cfg.seed, "recon_tol_s": cfg.recon_tol_s,
      "restarts": cfg.restarts,
      "router": cfg.router, "replicas": cfg.replicas if cfg.router else None,
      "fabric": cfg.fabric, "overload": cfg.overload, "gray": cfg.gray,
      "fleet": cfg.fleet,
      "fleet_latent": cfg.fleet_latent if cfg.fleet else None,
      "fleet_kill_router_at_s": cfg.fleet_kill_router_at_s,
      "faults": [{"kind": p.kind, "node": p.node, "at_s": p.at_s,
                  "grace_s": p.grace_s} for p in cfg.faults],
    },
    "fault_windows": windows,
    "client": client,
    "server": server,
    # Runs with injected DELAY rules restrict TTFT reconciliation to the
    # median: the delay lands in the server's TTFT histogram for every
    # request, but the client TTFT sample covers only streamed ones — a
    # delay hitting non-streamed requests puts the slow observations on
    # exactly one side, making the tails structurally incomparable (the
    # token_seconds median-only precedent, applied per run). Keyed on the
    # rules' ACTIONS: error/drop/kill rules phases keep the full check.
    "reconciliation": verdicts.reconcile(
      client, server, cfg.recon_tol_s,
      quantile_overrides=({"ttft_seconds": (0.5,)} if any(
        p.kind == "rules" and any(str(r.get("action")) == "delay"
                                  for r in (p.rules or []))
        for p in cfg.faults) else None)),
    "aborts": aborts,
    "alerts": alerts,
    "drift": drift,
    "history": verdicts.summarize_history(ring.last_history),
    "anatomy": verdicts.summarize_anatomy(ring.last_anatomy),
    "flight_dumps": {
      node_id: {"reason": d.get("reason"), "events": len(d.get("events") or ()),
                "snapshots": len(d.get("snapshots") or ())}
      for node_id, d in (dumps or {}).items()
    },
    "leaks": verdicts.leak_check(settle_a, settle_b),
    "drained": drained,
  }
  if cfg.overload and t_wall_load_start is not None:
    # Abort evidence gets a 45 s tail past the burst: a queue built during
    # the window would shed as "stalled" aborts up to a stall timeout later
    # — exactly the failure the gate must have prevented.
    t0 = t_wall_load_start + float(cfg.overload["at_s"]) - 1.0
    t1 = (t_wall_load_start + float(cfg.overload["at_s"])
          + float(cfg.overload.get("seconds", 0.0)) + 45.0)
    report["overload"] = verdicts.summarize_overload(
      records, events, [{"t0": t0, "t1": t1}],
      server.get("admission_rejections", 0.0))
  if cfg.router:
    report["router"] = verdicts.summarize_router(
      ring.last_router, ring.router_track, expect_drain=cfg.gray is not None,
      baseline=base_router)
  if cfg.fabric:
    # Load-window deltas of the cross-replica KV fabric counters (summed
    # over replicas — only the decode side imports, but the sum stays
    # correct if roles ever mix) plus the router's chain bookkeeping.
    rt, base_rt = (ring.last_router or {}), (base_router or {})

    def fabric_delta(prom: str) -> float:
      return (_sum_counter(ring.last_metrics, prom)
              - _sum_counter(base_metrics, prom))

    report["fabric"] = {
      "hits": fabric_delta("xot_kv_fabric_hits_total"),
      "misses": fabric_delta("xot_kv_fabric_misses_total"),
      "errors": fabric_delta("xot_kv_fabric_errors_total"),
      "bytes": fabric_delta("xot_kv_fabric_bytes_total"),
      "router_chained": max(0, int(rt.get("fabric_chained_total") or 0)
                            - int(base_rt.get("fabric_chained_total") or 0)),
      "router_chain_failures": max(
        0, int(rt.get("fabric_chain_failures_total") or 0)
        - int(base_rt.get("fabric_chain_failures_total") or 0)),
      # The smoke's whole point: a disaggregated ring that never imports
      # KV is just a slow router, so the verdict requires a real hit.
      "expect_hit": True,
    }
  if cfg.fleet:
    report["fleet"] = verdicts.summarize_fleet(
      ring.fleet_status, base_fleet, ring.last_router, base_router,
      holders=sorted(h for h in ring.fleet_holders if h),
      expect={
        # Each expectation is keyed on whether the run actually staged the
        # fault that produces it — a custom fault schedule only has to
        # clear the bars for what it injected.
        "respawn": any(p.kind == "kill" for p in cfg.faults),
        "scale_up": cfg.overload is not None,
        "hedge_win": any(
          p.kind == "rules" and any(str(r.get("action")) == "delay"
                                    for r in (p.rules or []))
          for p in cfg.faults),
        "holder_change": any(p.kind == "kill_router" for p in cfg.faults),
      })
  if not drained:
    leaked = report["leaks"]
    leaked["ok"] = False
    leaked.setdefault("active_requests", {})["<drain-timeout>"] = 1.0
  return report
