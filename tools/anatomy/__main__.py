"""CLI: fetch and render a node's latency anatomy.

  python -m tools.anatomy http://127.0.0.1:52415
  python -m tools.anatomy http://127.0.0.1:52415 --request-id <rid>
  python -m tools.anatomy http://127.0.0.1:52415 --diff 300
  python -m tools.anatomy http://127.0.0.1:52415 --chrome trace.json [--trace-id ID]
  python -m tools.anatomy saved_anatomy.json      # render a saved payload

The `--chrome` export plus Perfetto is the two-command postmortem workflow
documented in README "Latency anatomy".
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.parse
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
if str(REPO) not in sys.path:
  sys.path.insert(0, str(REPO))

from tools.anatomy import render


def _fetch(url: str, timeout: float = 10.0) -> dict:
  with urllib.request.urlopen(url, timeout=timeout) as r:
    return json.loads(r.read())


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(
    prog="python -m tools.anatomy",
    description="Render a node's /v1/anatomy latency breakdown")
  parser.add_argument("source", help="node base URL (http://host:port) or a saved JSON payload")
  parser.add_argument("--request-id", help="render ONE request's breakdown")
  parser.add_argument("--diff", type=float, metavar="SECONDS",
                      help="two-window 'which stage grew' diff")
  parser.add_argument("--chrome", metavar="OUT",
                      help="save the skew-corrected Chrome trace export (Perfetto-loadable)")
  parser.add_argument("--trace-id", help="restrict --chrome to one trace")
  parser.add_argument("--json", action="store_true", help="print raw JSON instead of a table")
  args = parser.parse_args(argv)

  if args.source.startswith(("http://", "https://")):
    base = args.source.rstrip("/")
    if args.chrome:
      query = {"format": "chrome"}
      if args.trace_id:
        query["trace_id"] = args.trace_id
      url = f"{base}/v1/traces?{urllib.parse.urlencode(query)}"
      try:
        payload = _fetch(url)
      except Exception as e:
        print(f"fetch {url} failed: {e}", file=sys.stderr)
        return 2
      Path(args.chrome).write_text(json.dumps(payload) + "\n")
      print(f"wrote {len(payload.get('traceEvents') or [])} trace events to {args.chrome} "
            "(load in https://ui.perfetto.dev or chrome://tracing)")
      return 0
    if args.request_id:
      url = f"{base}/v1/anatomy?request_id={urllib.parse.quote(args.request_id)}"
    elif args.diff is not None:
      url = f"{base}/v1/anatomy?diff={args.diff:g}"
    else:
      url = f"{base}/v1/anatomy"
    try:
      payload = _fetch(url)
    except Exception as e:
      print(f"fetch {url} failed: {e}", file=sys.stderr)
      return 2
  else:
    payload = json.loads(Path(args.source).read_text())

  print(json.dumps(payload, indent=1) if args.json else render(payload))
  return 0


if __name__ == "__main__":
  sys.exit(main())
