"""Latency-anatomy CLI helpers: render `/v1/anatomy` payloads as terminal
tables.

`python -m tools.anatomy http://host:port` prints the ring-wide per-stage
percentile table; `--request-id` renders one request's waterfall-style
breakdown; `--diff SECONDS` renders the two-window "which stage grew"
comparison; `--chrome OUT.json` saves the skew-corrected Chrome trace
export for Perfetto. Pure rendering lives here so it is unit-testable
without a server.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional


def _fmt_s(v: Optional[float]) -> str:
  if v is None:
    return "-"
  if v >= 1.0:
    return f"{v:.3f}s"
  return f"{v * 1e3:.1f}ms"


def _fmt_pct(v: Optional[float]) -> str:
  return "-" if v is None else f"{v * 100:.1f}%"


def render_breakdown(breakdown: Dict[str, Any]) -> str:
  """One request's stage table, largest contributor first, with the
  explicit unattributed residual and per-stage skew-uncertainty bound."""
  lines = [
    f"request {breakdown.get('request_id')}  "
    f"e2e {_fmt_s(breakdown.get('e2e_s'))}  "
    f"(trace {breakdown.get('trace_id')})",
    f"{'stage':<24} {'secs':>10} {'share':>8} {'± skew':>10}",
  ]
  stages = breakdown.get("stages") or {}
  for name, entry in sorted(stages.items(), key=lambda kv: -kv[1].get("secs", 0.0)):
    lines.append(f"{name:<24} {_fmt_s(entry.get('secs')):>10} "
                 f"{_fmt_pct(entry.get('share')):>8} "
                 f"{_fmt_s(entry.get('uncertainty_s')):>10}")
  offsets = breakdown.get("offsets") or {}
  for node, off in sorted(offsets.items()):
    lines.append(f"  clock[{node}]: offset {float(off.get('offset_ns', 0.0)) / 1e6:+.3f}ms "
                 f"± {float(off.get('uncertainty_ns', 0.0)) / 1e6:.3f}ms ({off.get('via')})")
  return "\n".join(lines)


def render_percentiles(payload: Dict[str, Any]) -> str:
  """The ring-wide per-stage contribution table (/v1/anatomy default)."""
  lines = [
    f"node {payload.get('node_id')}  breakdowns {payload.get('breakdowns')} "
    f"(lifetime {payload.get('total')})",
    f"{'stage':<24} {'secs p50':>10} {'secs p95':>10} {'share p50':>10} {'share p95':>10}",
  ]
  stages = payload.get("stages") or {}
  for name, entry in sorted(stages.items(), key=lambda kv: -kv[1].get("secs_p50", 0.0)):
    lines.append(f"{name:<24} {_fmt_s(entry.get('secs_p50')):>10} "
                 f"{_fmt_s(entry.get('secs_p95')):>10} "
                 f"{_fmt_pct(entry.get('share_p50')):>10} "
                 f"{_fmt_pct(entry.get('share_p95')):>10}")
  return "\n".join(lines)


def render_diff(payload: Dict[str, Any]) -> str:
  """The two-window "which stage grew" table (/v1/anatomy?diff=W)."""
  recent = payload.get("recent") or {}
  prev = payload.get("previous") or {}
  lines = [
    f"diff over {payload.get('window_s')}s windows: "
    f"recent n={recent.get('n')} vs previous n={prev.get('n')}",
    f"{'stage':<24} {'previous':>10} {'recent':>10} {'delta':>10}",
  ]
  deltas = payload.get("delta") or {}
  for name, d in sorted(deltas.items(), key=lambda kv: -kv[1]):
    lines.append(f"{name:<24} {_fmt_s((prev.get('stages') or {}).get(name)):>10} "
                 f"{_fmt_s((recent.get('stages') or {}).get(name)):>10} "
                 f"{'+' if d >= 0 else ''}{_fmt_s(abs(d)) if d >= 0 else '-' + _fmt_s(abs(d))}")
  grown = payload.get("grown")
  lines.append(f"grown: {grown if grown else '(no stage grew / empty window)'}")
  return "\n".join(lines)


def render(payload: Dict[str, Any]) -> str:
  """Dispatch on payload shape: one breakdown, a diff, or the percentile
  rollup."""
  if "grown" in payload or "delta" in payload:
    return render_diff(payload)
  if "e2e_s" in payload:
    return render_breakdown(payload)
  return render_percentiles(payload)
