"""CLI: fetch and render a node's metrics history.

  python -m tools.history http://127.0.0.1:52415
  python -m tools.history http://127.0.0.1:52415 --diff 600
  python -m tools.history http://127.0.0.1:52415 --metric decode_tok_s --window 3600
  python -m tools.history saved_history.json     # render a saved payload

The no-flag call plus `--diff` is the two-command workflow documented in
README "Metrics history & drift": first "what does the record say", then
"which metric moved".
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.parse
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
if str(REPO) not in sys.path:
  sys.path.insert(0, str(REPO))

from tools.history import render


def _fetch(url: str, timeout: float = 10.0) -> dict:
  with urllib.request.urlopen(url, timeout=timeout) as r:
    return json.loads(r.read())


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(
    prog="python -m tools.history",
    description="Render a node's /v1/history downsampled metrics record")
  parser.add_argument("source", help="node base URL (http://host:port) or a saved JSON payload")
  parser.add_argument("--window", type=float, metavar="SECONDS",
                      help="bound the record to the trailing window")
  parser.add_argument("--metric", help="render ONE gauge's value series")
  parser.add_argument("--diff", type=float, metavar="SECONDS",
                      help="two-window 'which metric moved' diff")
  parser.add_argument("--json", action="store_true", help="print raw JSON instead of tables")
  args = parser.parse_args(argv)

  if args.source.startswith(("http://", "https://")):
    base = args.source.rstrip("/")
    if args.diff is not None:
      url = f"{base}/v1/history?diff={args.diff:g}"
    else:
      query = {}
      if args.window is not None:
        query["window"] = f"{args.window:g}"
      if args.metric:
        query["metric"] = args.metric
      url = f"{base}/v1/history" + (f"?{urllib.parse.urlencode(query)}" if query else "")
    try:
      payload = _fetch(url)
    except Exception as e:
      print(f"fetch {url} failed: {e}", file=sys.stderr)
      return 2
  else:
    payload = json.loads(Path(args.source).read_text())

  print(json.dumps(payload, indent=1) if args.json else render(payload, metric=args.metric))
  return 0


if __name__ == "__main__":
  sys.exit(main())
