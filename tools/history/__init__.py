"""tools/history: render a node's /v1/history metrics record.

The endpoint serves the downsampled gauge time-series (and the "which
metric moved" diff) as JSON; this module turns either payload into the
terminal tables the README's two-command workflow documents. Stdlib-only
on purpose, like tools/anatomy: CI and operators call it without touching
the serving stack's dependencies.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional


def _fmt(v: Any) -> str:
  if v is None:
    return "—"
  if isinstance(v, bool):
    return "yes" if v else ""
  if isinstance(v, float):
    return f"{v:g}"
  return str(v)


def _table(headers: List[str], rows: List[List[Any]]) -> str:
  cells = [headers] + [[_fmt(c) for c in row] for row in rows]
  widths = [max(len(r[i]) for r in cells) for i in range(len(headers))]
  lines = ["  ".join(h.ljust(w) for h, w in zip(cells[0], widths))]
  lines.append("  ".join("-" * w for w in widths))
  for row in cells[1:]:
    lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
  return "\n".join(lines)


def render_diff(payload: Dict[str, Any]) -> str:
  """The ?diff= payload: per-metric before/after means, worst mover first."""
  rows = payload.get("rows") or []
  out = [f"history diff over {_fmt(payload.get('window_s'))}s windows "
         f"(node {payload.get('node_id', '?')})"]
  moved = payload.get("moved")
  out.append(f"moved: {moved}" if moved else "moved: nothing worsened")
  if rows:
    out.append("")
    out.append(_table(
      ["metric", "before", "after", "delta", "worse_by", "bad-direction"],
      [[r.get("metric"), r.get("before"), r.get("after"), r.get("delta"),
        r.get("worse_by"), r.get("worse")] for r in rows]))
  return "\n".join(out) + "\n"


def render(payload: Dict[str, Any], metric: Optional[str] = None) -> str:
  """The /v1/history payload: store stats, trailing means, cluster
  compacts, and (for a single-metric query) the value series."""
  if "rows" in payload and "moved" in payload:
    return render_diff(payload)
  tiers = payload.get("tiers") or {}
  out = [
    f"metrics history (node {payload.get('node_id', '?')}): "
    f"enabled={payload.get('enabled')} sample_s={_fmt(payload.get('sample_s'))} "
    f"samples_total={payload.get('samples_total')} "
    f"restarts={payload.get('restarts')}",
    f"tiers: fine={tiers.get('fine')} mid={tiers.get('mid')} old={tiers.get('old')}"
    + (f"  spool: {payload['spool']}" if payload.get("spool") else ""),
  ]
  trailing = payload.get("trailing") or {}
  if trailing:
    out += ["", "trailing means (drift window):", _table(
      ["metric", "mean"], [[k, v] for k, v in sorted(trailing.items())])]
  rows = payload.get("rows") or []
  if metric and rows:
    out += ["", f"series: {metric}", _table(
      ["ts", "dur_s", "samples", "value", "restart"],
      [[r.get("ts"), r.get("dur_s"), r.get("samples"), r.get("value"),
        r.get("restart")] for r in rows[-64:]])]
  elif rows:
    out.append(f"\nrows retained: {len(rows)} "
               "(pass --metric to render one gauge's series)")
  cluster = payload.get("cluster") or {}
  peers = {nid: c for nid, c in cluster.items() if nid != payload.get("node_id")}
  if peers:
    out += ["", "cluster compacts (trailing means per node):"]
    metrics = sorted({m for c in cluster.values()
                      for m in (c.get("trailing") or {})})
    out.append(_table(
      ["node"] + metrics + ["restarts", "stale"],
      [[nid] + [(c.get("trailing") or {}).get(m) for m in metrics]
       + [c.get("restarts"), c.get("stale")]
       for nid, c in sorted(cluster.items())]))
  return "\n".join(out) + "\n"
