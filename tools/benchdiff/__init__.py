"""benchdiff: make bench harvests comparable.

Every TPU harvest lands a `BENCH_*.json` in the repo root, and until now the
only way to answer "did this round regress?" was a human reading two JSON
blobs next to PERF.md. This tool owns that comparison:

- `diff_records` / `render_markdown`: per-metric deltas between any two
  bench records (or a record vs the `BENCH_BASELINE.json` bar), with
  per-metric noise thresholds and direction awareness (tok/s up = better,
  latency down = better) so a 1% wiggle reads as noise, not a headline.
- `check_repo`: the CI gate — every committed bench file must parse, carry
  a throughput number, and respect the same physical-plausibility rules the
  bench harness enforces at measurement time (HBM% within the ceiling,
  MFU <= 100, token cross-checks honored) — a hand-edited or corrupted
  harvest file fails CI instead of silently becoming the record.
- `perf_md_section` / `check_perf_md` / `write_perf_md`: PERF.md's
  measured-results table is GENERATED from the committed JSONs between
  BEGIN/END markers and drift-checked in CI, exactly like the README knob
  table — the markdown can no longer disagree with the data files.

Stdlib-only on purpose: CI runs it before any heavyweight import, and the
bench parent process can call it without touching jax.
"""
from __future__ import annotations

import json
import math
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

BEGIN_MARK = "<!-- BEGIN BENCH RESULTS (generated: python -m tools.benchdiff --write-perf-md) -->"
END_MARK = "<!-- END BENCH RESULTS -->"

# Fields that describe the CONFIG of a run, not its performance — identical
# configs are a precondition of a meaningful diff, not a delta to report.
CONFIG_KEYS = frozenset({
  "n_params", "param_bytes", "prefill_len", "decode_tokens", "long_ctx",
  "n_devices", "concurrent_n", "elapsed_s", "t", "recorded", "n", "rc",
  "predicted_weight_bytes", "predicted_decode_bytes_per_tok",
  "predicted_flops_per_tok", "roofline_tok_s", "int8_roofline_tok_s",
  "int4_roofline_tok_s",
})

# Per-metric relative noise floors (fraction): within this band the verdict
# is "within noise" regardless of sign. Unlisted metrics take DEFAULT_NOISE.
NOISE = {
  "tok_s": 0.05,
  "value": 0.05,
  "ttft_ms": 0.15,  # TTFT through the tunnel jitters hard run to run
  "per_token_ms": 0.05,
  "long_tok_s": 0.07,
  "long_prefill_s": 0.10,
  "concurrent_tok_s": 0.07,
  # Speculation throughput is acceptance-dependent (data-dependent draft
  # hits), so both spec stages — and their off-arms, measured in the same
  # noisy window — get the wider concurrent-style floor.
  "spec_tok_s": 0.07,
  "spec_off_tok_s": 0.07,
  "specpaged_tok_s": 0.07,
  "specpaged_off_tok_s": 0.07,
  # Mesh on/off arms share one process and compile twice; collective
  # placement jitters the small-model window like the concurrent stage.
  "mesh_tok_s": 0.07,
  "mesh_off_tok_s": 0.07,
  "mesh_speedup": 0.07,
  "mesh_ttft_ms": 0.15,
  # The vkv stage's three arms compile three engines in one window; the
  # arm ratios inherit both arms' jitter, so they ride the wide floor too.
  # The zero bars (vkv_unpage_calls, vkv_commit_copy_bytes) are direction
  # rules, not noise entries — any move off 0 is REGRESSED.
  "vkv_int8_tok_s": 0.07,
  "vkv_int8_contig_tok_s": 0.07,
  "vkv_bf16_tok_s": 0.07,
  "vkv_paged_speedup": 0.07,
  "vkv_int8_speedup": 0.07,
  "vkv_ttft_ms": 0.15,
  # The fabric stage's TTFT pair compiles two engines in one window and the
  # warm arm's cost is dominated by a host-tier restore — both arms (and
  # their ratio) ride the wide TTFT-style floors.
  "fabric_cold_ttft_s": 0.15,
  "fabric_warm_ttft_s": 0.15,
  "fabric_speedup": 0.07,
}
DEFAULT_NOISE = 0.05
# Soak latency percentiles ride a loaded CPU ring in CI: run-to-run jitter
# is far above bench-grade noise, so soak-to-soak drift gates at a wider
# floor. Zero-tolerance counters (false aborts, leaks) are NOT noise-floored
# — their direction rule flags any increase from 0 as REGRESSED.
SOAK_LATENCY_NOISE = 0.30

SOAK_SCHEMA = "xot-soak-v1"


def is_soak_file(record: Dict[str, Any]) -> bool:
  """A `SOAK_*.json` verdict report written by `python -m tools.soak`."""
  return isinstance(record, dict) and record.get("schema") == SOAK_SCHEMA


def soak_metrics_of(record: Dict[str, Any]) -> Dict[str, float]:
  """The flat metric dict tools/soak stamps into every report
  (`flatten_metrics`): latency percentiles, rates, abort/leak counters."""
  out = {}
  for k, v in (record.get("metrics") or {}).items():
    if _is_number(v):
      out[k] = float(v)
  return out


def _is_soak_latency(name: str) -> bool:
  return ((name.startswith("client_") or name.startswith("server_"))
          and name.endswith("_s"))


def _is_number(v: Any) -> bool:
  return isinstance(v, (int, float)) and not isinstance(v, bool) and math.isfinite(v)


def load_bench(path: Path) -> Optional[Dict[str, Any]]:
  """A bench file as a flat {field: value} record, or None when the file
  holds no extractable record. Three committed shapes are understood: the
  flat result line (`BENCH_TPU_*.json`), the driver roundfile whose `tail`
  embeds the result line (`BENCH_r0*.json`), and `BENCH_BASELINE.json`'s
  keyed form (returned as-is — `is_baseline_file` distinguishes it)."""
  try:
    data = json.loads(Path(path).read_text())
  except (OSError, json.JSONDecodeError):
    return None
  if not isinstance(data, dict):
    return None
  if "tail" in data and "metric" not in data:
    # Driver roundfile: the result line is the last parseable JSON object
    # in the captured tail.
    for line in reversed(str(data.get("tail", "")).splitlines()):
      line = line.strip()
      if line.startswith("{"):
        try:
          rec = json.loads(line)
        except json.JSONDecodeError:
          continue
        if isinstance(rec, dict) and ("metric" in rec or "tok_s" in rec):
          return rec
    return None
  return data


def is_baseline_file(record: Dict[str, Any]) -> bool:
  """BENCH_BASELINE.json shape: every value is a dict keyed
  `model:platform:method` with its own tok_s."""
  return bool(record) and all(
    isinstance(v, dict) and "tok_s" in v for v in record.values())


def record_model_platform(record: Dict[str, Any]) -> Tuple[str, str]:
  """(model_id, platform) of a flat record; the model falls out of the
  `metric` name (`decode_tok_s_<model-with-underscores>_bf16_1chip`) when
  no explicit model_id survived `_emit`'s field pass-through."""
  model = record.get("model_id")
  if not model:
    m = re.match(r"decode_tok_s_(.+)_bf16_1chip$", str(record.get("metric", "")))
    model = m.group(1).replace("_", "-") if m else "unknown"
  return str(model), str(record.get("platform", "unknown"))


def metrics_of(record: Dict[str, Any]) -> Dict[str, float]:
  """The record's numeric performance metrics. `value` (the emit alias of
  the fused-decode headline) folds into `tok_s` so flat records and
  baseline entries diff under one name."""
  out: Dict[str, float] = {}
  for k, v in record.items():
    if k in CONFIG_KEYS or not _is_number(v):
      continue
    out[k] = float(v)
  if "tok_s" not in out and _is_number(record.get("value")):
    out["tok_s"] = float(record["value"])
  out.pop("value", None)
  return out


def baseline_metrics_for(baseline: Dict[str, Any],
                         record: Dict[str, Any]) -> Tuple[Optional[str], Dict[str, float]]:
  """The baseline bar matching a flat record: keyed per
  (model, platform, method) so a CPU smoke run never diffs against the TPU
  bar. Returns (key or None, metrics)."""
  model, platform = record_model_platform(record)
  key = f"{model}:{platform}:fused"
  entry = baseline.get(key)
  if not isinstance(entry, dict):
    return None, {}
  return key, {k: float(v) for k, v in entry.items() if _is_number(v)}


# Soak counters whose every increase is bad vs. informational counters whose
# magnitude depends on the injected fault schedule. Zero-tolerance is
# reserved for the counters a green VERDICT already guarantees are zero
# (false aborts, leaks): a drift gate on them can never flag a green run.
# Raw watchdog aborts and client errors are legitimately nonzero when a kill
# lands awkwardly (in-window, excused by the verdict) — gating those would
# make CI flake on fault-timing luck, so they report as info.
_SOAK_DOWN = frozenset({
  "false_aborts", "leaked_requests", "pool_page_leaks",
  # An SLO alert firing with no injected fault to blame is the alerting
  # twin of a false abort: the rules paged on healthy traffic. A green
  # verdict guarantees zero, so the drift gate can never flag a green run.
  "alert_firings_outside_fault_windows",
  # A watchdog abort INSIDE the overload window means above-capacity load
  # was shed as "stalled" aborts instead of admission-gate 429s — the exact
  # PR 8 failure mode the front door exists to close. A green verdict
  # guarantees zero, so the gate can never flag a green run.
  "overload_watchdog_aborts",
  # Traffic routed to a replica while it was out of rotation: the router
  # kept placing load on a drained/probing replica — failover is broken.
  "router_routed_while_out",
  # A perf_drift firing with no injected fault to blame: the chronic
  # sentinel named rot on healthy traffic — the drift twin of a false
  # abort. A green verdict guarantees zero, so the gate can never flag a
  # green run.
  "drift_firings_outside_fault_windows",
  # A KV-fabric transfer dropped mid-smoke (peer error, torn blob, digest
  # mismatch) between two healthy localhost processes: the transport is
  # broken, not degraded. A green verdict guarantees zero (tools/soak
  # evaluate reds on any), so the gate can never flag a green run.
  "fabric_transfer_failures",
  # A fleet respawn that never came back healthy is the outage the elastic
  # controller exists to prevent; a hedged request streaming tokens from
  # BOTH legs is a double-billed response (the loser was not cancelled).
  # A green verdict guarantees both are zero, so the gate can never flag a
  # green run.
  "fleet_respawn_failures",
  "hedge_both_streamed",
})
_SOAK_INFO = frozenset({
  "requests_submitted", "requests_ok", "request_errors",
  "request_restarts_total", "peer_evictions_total", "hop_retries_total",
  "dedup_drops_total", "watchdog_aborts_total",
  # Admission/router magnitudes depend on the injected overload/gray
  # schedule (an overload burst is SUPPOSED to shed, a gray failure is
  # supposed to drain), so their drift is informational; the zero bars
  # above are what a green verdict actually guarantees.
  "requests_rejected", "admission_rejections_total", "overload_client_rejected",
  "router_drains_total", "router_readmits_total", "router_prefetch_announced",
  # Raw firing counts depend on the fault schedule (a kill is SUPPOSED to
  # fire the error-rate rule), so magnitude drift is informational.
  "alert_firings_total", "alerts_fired_and_resolved",
  # Drift magnitudes depend on the injected schedule too (a gray phase is
  # SUPPOSED to deviate from the fleet median); the zero bar above is what
  # a green verdict guarantees. History volumes scale with run length.
  "drift_firings_total", "router_drift_named",
  "history_samples_total", "history_restarts_total",
  # Latency-anatomy shape: reservoir depth varies with load; the
  # unattributed share is gated ABSOLUTELY below (_ANATOMY_MAX_UNATTRIBUTED)
  # rather than by drift, so both report as info in diffs.
  "anatomy_breakdowns", "anatomy_unattributed_share",
  # Fabric chain/import magnitudes scale with the prompt mix (session
  # reuse satisfies locally, only fresh prompts chain), and a chain
  # FAILURE's documented degradation is a plain cold forward — the soak
  # verdict owns the >= 1 hit bar; drift here is informational.
  "kv_fabric_misses", "fabric_chained", "fabric_chain_failures",
  # Fleet actuation and hedge magnitudes are dictated by the injected
  # fault schedule (a SIGKILL is SUPPOSED to respawn, a surge is SUPPOSED
  # to scale up, a stall is SUPPOSED to hedge); the verdict owns the >= 1
  # expectations and the zero bars above own the failure counters.
  "fleet_respawns", "fleet_deaths", "fleet_scale_ups", "fleet_scale_downs",
  "fleet_spawn_failures", "hedges_fired", "hedges_won", "hedge_cancelled",
})

# A committed green soak whose stage breakdowns leave more than this
# fraction of e2e unattributed is not evidence — the anatomy can't say
# where the time went, so it must not sit in the tree as the record.
_ANATOMY_MAX_UNATTRIBUTED = 0.5


def _direction(name: str) -> str:
  """'up' = higher is better, 'down' = lower is better, 'info' = report the
  delta but render no verdict (utilization, counts, ratios whose sign has
  no universal meaning)."""
  if name in _SOAK_DOWN:
    return "down"
  if name in _SOAK_INFO:
    return "info"
  if (name.endswith("tok_s") or name.endswith("speedup") or name.endswith("_rps")
      or name.endswith("_accept_rate") or name == "vs_baseline"):
    return "up"
  # Cross-replica KV reuse is the fabric's whole point: more imported
  # warm-prefix hits/bytes at the same workload = less cold prefill.
  if name.startswith("kv_fabric_hits") or name.startswith("kv_fabric_bytes"):
    return "up"
  # Paged-native zero-bars: any unpage gather or commit copy on a paged
  # path is a structural regression, not noise (zero baseline means any
  # increase reads REGRESSED with no floor to hide behind).
  if name.endswith("_unpage_calls") or name.endswith("_commit_copy_bytes"):
    return "down"
  # Defrag copies at an identical workload are pure overhead (each move is
  # a page of HBM traffic the arena paid to stay compact) — fewer is
  # better; the fragmentation gauge itself stays info below (a snapshot of
  # workload shape, not a cost).
  if name.endswith("_defrag_moves"):
    return "down"
  if name.endswith("_ms") or name.endswith("_s"):
    return "down"
  return "info"


def diff_records(current: Dict[str, float], baseline: Dict[str, float],
                 noise: Optional[Dict[str, float]] = None) -> List[Dict[str, Any]]:
  """Per-metric delta rows, baseline-ordered then current-only extras. A
  metric missing from the baseline is reported as `new` (never a failure:
  bench stages accrete round over round); one missing from the current run
  is `missing` — that IS worth a look, a stage stopped reporting."""
  noise = {**NOISE, **(noise or {})}
  rows: List[Dict[str, Any]] = []
  for name in list(baseline) + [m for m in current if m not in baseline]:
    base = baseline.get(name)
    cur = current.get(name)
    row: Dict[str, Any] = {"metric": name, "baseline": base, "current": cur}
    if base is None:
      row.update(delta=None, pct=None, verdict="new")
    elif cur is None:
      row.update(delta=None, pct=None, verdict="missing")
    else:
      delta = cur - base
      pct = (delta / abs(base) * 100.0) if base else None
      row.update(delta=round(delta, 4), pct=round(pct, 2) if pct is not None else None)
      direction = _direction(name)
      if name in noise:
        floor = noise[name] * 100.0
      elif name in _SOAK_DOWN:
        floor = 0.0  # zero-tolerance: any new abort/leak/error is a regression
      elif _is_soak_latency(name):
        floor = SOAK_LATENCY_NOISE * 100.0
      else:
        floor = DEFAULT_NOISE * 100.0
      if direction == "info":
        row["verdict"] = "info"
      elif pct is None:
        # Zero baseline: percent is undefined but the sign still is —
        # a counter moving 0 -> N must not hide behind "within noise".
        row["verdict"] = ("within noise" if delta == 0 else
                          "improved" if (delta > 0) == (direction == "up") else "REGRESSED")
      elif abs(pct) <= floor:
        row["verdict"] = "within noise"
      else:
        better = (pct > 0) == (direction == "up")
        row["verdict"] = "improved" if better else "REGRESSED"
    rows.append(row)
  return rows


def render_markdown(rows: List[Dict[str, Any]], title: str = "") -> str:
  def fmt(v):
    if v is None:
      return "—"
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
      return str(int(v))
    return f"{v:g}" if isinstance(v, float) else str(v)

  lines = []
  if title:
    lines.append(f"### {title}\n")
  lines.append("| Metric | Baseline | Current | Δ | Δ% | Verdict |")
  lines.append("| --- | --- | --- | --- | --- | --- |")
  for r in rows:
    pct = f"{r['pct']:+.2f}%" if r.get("pct") is not None else "—"
    lines.append(f"| {r['metric']} | {fmt(r['baseline'])} | {fmt(r['current'])} "
                 f"| {fmt(r['delta'])} | {pct} | {r['verdict']} |")
  return "\n".join(lines) + "\n"


# ----------------------------------------------------------------- CI gate


# The only committed harvests measured before bench.py carried the
# plausibility verdict (the round-2 lying-backend artifact is kept as
# evidence, PERF.md "Measurement integrity"). Frozen by NAME so a new file
# cannot ride the exemption by simply omitting the field.
_PRE_GATE_FILES = frozenset({"BENCH_r02.json"})


def _plausibility_findings(name: str, rec: Dict[str, Any]) -> List[str]:
  """The measurement-integrity rules bench.py enforces live, re-applied to
  the committed file — a hand-edited or bit-rotted harvest cannot sit in
  the tree claiming over-roofline physics without its `implausible` flag."""
  findings = []
  if "implausible" not in rec:
    if name in _PRE_GATE_FILES:
      return findings
    # Every emit since the gate landed includes the field; a modern record
    # without it is a finding on its own, and the physics checks below
    # still run against it (flagged=False).
    findings.append(f"{name}: record carries no `implausible` verdict "
                    "(only the pre-gate history files may omit it)")
  flagged = bool(rec.get("implausible"))
  checks = (
    ("hbm_bw_pct", 110.0, "exceeds the physical HBM ceiling"),
    ("mfu_pct", 100.0, "exceeds 100% MFU"),
    ("prefill_mfu_pct", 100.0, "exceeds 100% prefill MFU"),
    # The cost-model fields bench.py's live gate keys on since PR 7 —
    # absent from pre-PR-7 harvests, required-plausible in every new one.
    ("predicted_hbm_util_pct", 110.0,
     "exceeds the physical HBM ceiling (cost-model prediction)"),
    ("predicted_mfu_pct", 100.0, "exceeds 100% MFU (cost-model prediction)"),
  )
  for field_name, limit, why in checks:
    v = rec.get(field_name)
    if _is_number(v) and v > limit and not flagged:
      findings.append(f"{name}: {field_name}={v} {why} but `implausible` is not set")
  for field_name in ("tokens_verified", "overlap_tokens_match"):
    if rec.get(field_name) is False and not flagged:
      findings.append(f"{name}: {field_name} is false but `implausible` is not set")
  roof = rec.get("roofline_tok_s")
  tok_s = rec.get("tok_s", rec.get("value"))
  if _is_number(roof) and _is_number(tok_s) and tok_s > 1.1 * roof and not flagged:
    findings.append(f"{name}: tok_s={tok_s} exceeds roofline_tok_s={roof} "
                    "but `implausible` is not set")
  return findings


def bench_files(root: Path) -> List[Path]:
  return sorted(Path(root).glob("BENCH_*.json"))


def soak_files(root: Path) -> List[Path]:
  return sorted(Path(root).glob("SOAK_*.json"))


def _soak_findings(name: str, rec: Dict[str, Any]) -> List[str]:
  """Gate one committed soak report: a red (or schema-less, or internally
  inconsistent) verdict must not sit in the tree as if it were the record."""
  findings = []
  if not is_soak_file(rec):
    return [f"{name}: not a recognized soak report (schema != {SOAK_SCHEMA!r})"]
  verdict = rec.get("verdict")
  if verdict != "green":
    findings.append(f"{name}: soak verdict is {verdict!r} — only green soaks may be committed "
                    f"(reasons: {'; '.join(map(str, rec.get('reasons') or ())) or 'none recorded'})")
  metrics = rec.get("metrics")
  if not isinstance(metrics, dict) or not any(_is_number(v) for v in metrics.values()):
    findings.append(f"{name}: soak report carries no flat `metrics` dict to diff")
  else:
    # Driven by _SOAK_DOWN so the drift gate and the green-contradiction
    # gate can never disagree about what zero-tolerance means.
    for zero_key in sorted(_SOAK_DOWN):
      v = metrics.get(zero_key)
      if _is_number(v) and v > 0 and verdict == "green":
        findings.append(f"{name}: metrics[{zero_key}]={v} contradicts the green verdict")
    # Stage-breakdown honesty: a green file carrying an anatomy section
    # must ATTRIBUTE the time it reports (absolute bound, not drift).
    share = metrics.get("anatomy_unattributed_share")
    if _is_number(share) and share > _ANATOMY_MAX_UNATTRIBUTED and verdict == "green":
      findings.append(
        f"{name}: metrics[anatomy_unattributed_share]={share} exceeds the "
        f"{_ANATOMY_MAX_UNATTRIBUTED:g} bound — the stage breakdown cannot say "
        "where the time went")
  return findings


def check_repo(root: Path) -> List[str]:
  """Schema + implausibility gate over every committed bench file, plus the
  PERF.md generated-section drift check. Returns human-readable findings
  (empty = gate passes)."""
  root = Path(root)
  findings: List[str] = []
  for path in bench_files(root):
    rec = load_bench(path)
    if rec is None:
      # A driver roundfile whose round FAILED (rc != 0) legitimately holds
      # no record — the failure is its record. Anything else is corrupt.
      try:
        raw = json.loads(path.read_text())
      except (OSError, json.JSONDecodeError):
        raw = None
      if not (isinstance(raw, dict) and "tail" in raw and raw.get("rc", 0) != 0):
        findings.append(f"{path.name}: no parseable bench record")
      continue
    if is_baseline_file(rec):
      for key, entry in sorted(rec.items()):
        if not _is_number(entry.get("tok_s")):
          findings.append(f"{path.name}: baseline entry {key!r} has no numeric tok_s")
      continue
    if not _is_number(rec.get("tok_s", rec.get("value"))):
      findings.append(f"{path.name}: record carries no numeric tok_s/value")
      continue
    findings.extend(_plausibility_findings(path.name, rec))
  for path in soak_files(root):
    try:
      rec = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
      findings.append(f"{path.name}: no parseable soak report")
      continue
    findings.extend(_soak_findings(path.name, rec))
  findings.extend(check_perf_md(root))
  return findings


# ------------------------------------------------- PERF.md generated table


def perf_md_section(root: Path) -> str:
  """The PERF.md measured-results table, generated from the committed
  on-chip harvest files (BENCH_TPU_*.json) against BENCH_BASELINE.json.
  Deterministic: sorted by filename, values straight from the JSONs."""
  root = Path(root)
  baseline_rec = load_bench(root / "BENCH_BASELINE.json") or {}
  lines = [
    BEGIN_MARK,
    "",
    "| File | tok/s | vs baseline | TTFT ms | HBM % | int8 tok/s | int4 tok/s | verified | implausible |",
    "| --- | --- | --- | --- | --- | --- | --- | --- | --- |",
  ]

  def cell(v):
    return str(v) if _is_number(v) else "—"

  for path in sorted(root.glob("BENCH_TPU_*.json")):
    rec = load_bench(path)
    if rec is None or is_baseline_file(rec):
      continue
    cur = metrics_of(rec)
    _, base = baseline_metrics_for(baseline_rec, rec)
    vs = (round(cur["tok_s"] / base["tok_s"], 3)
          if _is_number(cur.get("tok_s")) and _is_number(base.get("tok_s")) and base["tok_s"]
          else None)
    lines.append(
      f"| `{path.name}` | {cell(cur.get('tok_s'))} | {cell(vs)} "
      f"| {cell(cur.get('ttft_ms'))} | {cell(cur.get('hbm_bw_pct'))} "
      f"| {cell(cur.get('int8_tok_s'))} | {cell(cur.get('int4_tok_s'))} "
      f"| {str(bool(rec.get('tokens_verified', False))).lower()} "
      f"| {str(bool(rec.get('implausible', False))).lower()} |")
  if baseline_rec:
    lines.append("")
    lines.append("Baseline bars (`BENCH_BASELINE.json`): "
                 + ", ".join(f"`{k}` = {v.get('tok_s')} tok/s"
                             for k, v in sorted(baseline_rec.items())))
  lines += ["", END_MARK]
  return "\n".join(lines)


def _committed_section(text: str) -> Optional[str]:
  start = text.find(BEGIN_MARK)
  end = text.find(END_MARK)
  if start == -1 or end == -1 or end < start:
    return None
  return text[start:end + len(END_MARK)]


def check_perf_md(root: Path, perf_md: str = "PERF.md") -> List[str]:
  path = Path(root) / perf_md
  try:
    text = path.read_text()
  except OSError:
    return [f"{perf_md}: missing"]
  committed = _committed_section(text)
  if committed is None:
    return [f"{perf_md}: no `{BEGIN_MARK}` ... `{END_MARK}` block — "
            "add one and run `python -m tools.benchdiff --write-perf-md`"]
  if committed.strip() != perf_md_section(root).strip():
    return [f"{perf_md}: generated measured-results section is stale — "
            "run `python -m tools.benchdiff --write-perf-md`"]
  return []


def write_perf_md(root: Path, perf_md: str = "PERF.md") -> bool:
  """Regenerate the PERF.md section in place (True when the file changed).
  Appends the block at the end when no markers exist yet."""
  path = Path(root) / perf_md
  text = path.read_text()
  section = perf_md_section(root)
  committed = _committed_section(text)
  if committed is None:
    new_text = text.rstrip() + "\n\n## Measured results (generated)\n\n" + section + "\n"
  else:
    new_text = text.replace(committed, section)
  if new_text != text:
    path.write_text(new_text)
    return True
  return False
