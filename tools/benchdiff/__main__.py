"""CLI: `python -m tools.benchdiff` — diff bench harvests, gate CI.

Modes:
  python -m tools.benchdiff CURRENT.json --baseline BENCH_BASELINE.json
      per-metric markdown delta table (CURRENT may also be a directory:
      every BENCH_TPU_*/BENCH_r* file inside is diffed against the baseline)
  python -m tools.benchdiff A.json B.json
      diff two flat bench records directly (B is the baseline side)
  python -m tools.benchdiff --check
      CI gate: schema/implausibility over every committed BENCH_*.json plus
      the PERF.md generated-section drift check; exit 1 on findings
  python -m tools.benchdiff --write-perf-md
      regenerate PERF.md's measured-results section from the committed JSONs

Exit codes: 0 = ok, 1 = gate findings or a diffed metric REGRESSED beyond
its noise floor (suppress with --no-gate), 2 = usage error.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tools.benchdiff import (
  baseline_metrics_for, bench_files, check_repo, diff_records, is_baseline_file,
  is_soak_file, load_bench, metrics_of, perf_md_section, render_markdown,
  soak_files, soak_metrics_of, write_perf_md,
)


def _diff_one(current_path: Path, baseline_path: Path, out: list) -> int:
  current = load_bench(current_path)
  if current is None or is_baseline_file(current):
    print(f"benchdiff: {current_path} holds no flat bench record", file=sys.stderr)
    return 2
  baseline = load_bench(baseline_path)
  if baseline is None:
    print(f"benchdiff: {baseline_path} holds no bench record", file=sys.stderr)
    return 2
  if is_soak_file(current) or is_soak_file(baseline):
    # Soak-to-soak SLO drift: both sides must be soak verdict reports.
    if not (is_soak_file(current) and is_soak_file(baseline)):
      print("benchdiff: a soak report can only be diffed against another "
            "soak report", file=sys.stderr)
      return 2
    rows = diff_records(soak_metrics_of(current), soak_metrics_of(baseline))
    out.append(render_markdown(
      rows, title=f"{current_path.name} vs {baseline_path.name} [soak]"))
    return 1 if any(r["verdict"] == "REGRESSED" for r in rows) else 0
  if is_baseline_file(baseline):
    key, base_metrics = baseline_metrics_for(baseline, current)
    title = f"{current_path.name} vs {baseline_path.name} [{key or 'no matching bar'}]"
  else:
    base_metrics = metrics_of(baseline)
    title = f"{current_path.name} vs {baseline_path.name}"
  rows = diff_records(metrics_of(current), base_metrics)
  out.append(render_markdown(rows, title=title))
  return 1 if any(r["verdict"] == "REGRESSED" for r in rows) else 0


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(
    prog="python -m tools.benchdiff",
    description="Diff bench harvests with noise thresholds; gate committed "
                "bench files and PERF.md's generated section in CI.",
  )
  parser.add_argument("current", nargs="?", help="bench record (or directory of them) to diff")
  parser.add_argument("old", nargs="?", help="second record to diff against (baseline side)")
  parser.add_argument("--baseline", default=None,
                      help="baseline file (default: BENCH_BASELINE.json under --root)")
  parser.add_argument("--root", default=".", help="repo root (default: cwd)")
  parser.add_argument("--check", action="store_true",
                      help="schema/implausibility gate over committed bench files + PERF.md drift")
  parser.add_argument("--perf-md", action="store_true",
                      help="print the generated PERF.md measured-results section and exit")
  parser.add_argument("--write-perf-md", action="store_true",
                      help="regenerate PERF.md's measured-results section in place")
  parser.add_argument("--out", default=None, help="also write the markdown report to this file")
  parser.add_argument("--no-gate", action="store_true",
                      help="always exit 0 from a diff, even on regressions beyond noise")
  args = parser.parse_args(argv)
  root = Path(args.root)

  if args.perf_md:
    print(perf_md_section(root))
    return 0
  if args.write_perf_md:
    changed = write_perf_md(root)
    print("PERF.md updated" if changed else "PERF.md already current")
    return 0
  if args.check:
    findings = check_repo(root)
    for f in findings:
      print(f)
    if findings:
      print(f"\nbenchdiff: {len(findings)} finding(s)", file=sys.stderr)
      return 1
    print(f"benchdiff: {len(bench_files(root))} bench file(s) + "
          f"{len(soak_files(root))} soak report(s) clean, PERF.md section current")
    return 0

  if not args.current:
    parser.print_usage(sys.stderr)
    return 2
  current = Path(args.current)
  if not current.exists() and (root / current).exists():
    current = root / current
  baseline = Path(args.old) if args.old else Path(args.baseline or (root / "BENCH_BASELINE.json"))
  if not baseline.exists() and (root / baseline).exists():
    baseline = root / baseline

  out: list = []
  if current.is_dir():
    rcs = [_diff_one(p, baseline, out)
           for p in sorted(current.glob("BENCH_*.json"))
           if (rec := load_bench(p)) is not None and not is_baseline_file(rec)]
    rc = max(rcs, default=0)
  else:
    rc = _diff_one(current, baseline, out)
  report = "\n".join(out)
  print(report)
  if args.out:
    Path(args.out).write_text(report)
  if rc == 1 and args.no_gate:
    return 0
  return rc


if __name__ == "__main__":
  sys.exit(main())
